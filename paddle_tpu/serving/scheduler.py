"""Scheduler: admission queue + continuous-batching loop on top of
ServingEngine.

FCFS admission: whenever a slot is free and the queue is non-empty, the
head request is assigned to it MID-STREAM (engine.begin_prefill) and its
prefill advances one engine step per scheduling round
(engine.prefill_step) — the dense engine completes in one round, the
paged engine runs one CHUNK per round, so a long prompt's admission is
folded between decode waves and never stalls the other lanes (same
compiled programs throughout). Retirement (EOS / max_tokens / cache
horizon / timeout) frees slots between waves and the freed slot is
refilled in the same step() — a slot never idles while work is queued.

Paged-engine capacity (serving/paged) is handled here too: an exhausted
block pool at admission queues the head request behind the blocks it is
waiting for (or sheds it when nothing in flight could free them), and a
lane starved mid-decode is PREEMPTED BY RECOMPUTE — blocks freed,
request requeued with prompt + generated tokens (prefix-cache hits make
the re-prefill cheap), bounded by `max_preemptions`.

Resilience (docs/robustness.md; every path below is proven
by injection in scripts/chaos_serving.py):

  * a failed prefill or a non-finite decode lane resolves ONLY that
    request (finish_reason "error") — the rest of the batch keeps
    decoding the same compiled program; a streak of
    `prefill_fail_limit` CONSECUTIVE prefill failures across distinct
    requests escalates to graceful degradation, so a persistently
    broken engine cannot hide behind per-request isolation with
    /healthz still reporting "ok";
  * a decode-wave exception is retried up to `wave_retries` times with
    bounded exponential backoff (`retry_backoff_s`, doubling); an
    exhausted budget degrades the engine gracefully — in-flight
    requests resolve with "error", queued and new work is shed with
    "rejected", /healthz reports "degraded" — instead of a stack trace
    out of the wave loop;
  * admission control: `max_queue` bounds the queue (overflow sheds
    with finish_reason "rejected"), `drain()` stops admissions while
    accepted work runs to completion (/healthz: "draining").

Observability (docs/observability.md "SLO engine & fleet tracing"):
every round is split into admission / prefill_chunk / decode_wave /
host_dispatch phase spans (traced AND metered — the sampling tail is
fused inside the wave program, so it deliberately has no host-side
span), each wave's measured time is divided into the compiled
program's own cost analysis for the `serving_mfu` /
`serving_hbm_util` roofline gauges, and an optional `slo=SLOPolicy`
feeds completions into a burn-rate window served on /healthz.

Thread-model: submit() is safe from any producer thread (the bench
script's Poisson arrival generator); the wave loop itself runs wherever
run()/step() is called — the engine's compiled programs are driven from
one thread at a time.
"""
import collections
import threading
import time

from ..utils import flight_recorder, profiler, telemetry
from ..utils.profiler import RecordEvent
from . import blackbox
from .metrics import ServingMetrics
from .paged.block_pool import BlockPoolExhausted
from .request import Request, RequestState
from .slo import as_engine as _slo_as_engine


#: replica roles for the disaggregated fleet (serving/fleet/disagg.py):
#: a "prefill" scheduler runs ONLY chunked-prefill programs — each
#: completed prefill is exported as a block-level KV payload and parked
#: for the router (take_handoffs) instead of decoding; a "decode"
#: scheduler accepts ONLY handoff continuations (admission imports the
#: blocks, zero prefill-chunk programs run); "unified" is the classic
#: do-both replica.
ROLES = ("prefill", "decode", "unified")


class Scheduler:
    def __init__(self, engine, max_queue=None, completed_log=1024,
                 wave_retries=3, retry_backoff_s=0.05,
                 prefill_fail_limit=None, max_preemptions=3, slo=None,
                 role="unified", qos=None):
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        if role != "unified" and not hasattr(engine, "export_slot_kv"):
            raise ValueError(
                f"role {role!r} needs an engine with the block-level "
                "handoff surface (export_slot_kv / import_handoff — "
                "serving/paged)")
        self.role = role
        # optional multi-tenant QoS manager (serving/fleet/qos.py),
        # duck-typed: under_pressure(pool) gates weighted-fair admission
        # (pick_admission over the queue) — with qos=None the queue is
        # strict FCFS, pre-QoS behavior exactly
        self.qos = qos
        # prefill-role staging area: (request, payload) pairs whose
        # prefill completed this round, waiting for the router to hand
        # them to a decode replica (payload None = export failed)
        self._handoff_ready = []
        self.engine = engine
        self.max_queue = max_queue
        # chrome-trace process row for this scheduler's spans/requests
        # (0 = single-engine; a fleet Replica sets replica_id + 1 so
        # the router's merged trace shows each replica on its own row)
        self.trace_pid = 0
        # optional SLO tracking (serving/slo.py): completions feed the
        # sliding window, every round re-evaluates, and the burn-rate
        # verdict rides /healthz next to queue depth
        self.slo_engine = _slo_as_engine(slo)
        # optional observability plane (utils/timeseries + utils/anomaly,
        # attach_timeseries): the sampler banks every metric once per
        # working round, the alert manager runs its detector set.  The
        # engine's health-probe slot is NEWEST-WINS, so the SLO verdict
        # and the alert state must share ONE merged probe.
        self._sampler = None
        self._alerts = None
        if self.slo_engine is not None:
            engine.attach_health_probe(self._health_extras)
        # program flops/bytes per wave for the roofline gauges —
        # resolved NOW, at construction, not at the first wave: the
        # lowering-level cost analysis can stall for seconds on a real
        # model, and a stall between wave and token-emit would be
        # stamped into every in-flight request's inter-token gap,
        # spiking the very TPOT/SLO window it feeds. program_costs is
        # memoized per shape signature, so a fleet pays one lowering.
        # A speculative engine's wave is TWO programs (draft + verify):
        # their costs sum into the per-wave roofline numerators.
        costs = engine.program_costs()
        self._wave_cost = costs.get("decode_wave") or {}
        if "verify" in costs or "draft_wave" in costs:
            merged = {}
            for part in (costs.get("draft_wave"), costs.get("verify")):
                for k, v in (part or {}).items():
                    if isinstance(v, (int, float)):
                        merged[k] = merged.get(k, 0.0) + v
            self._wave_cost = merged
        self.last_wave_s = None
        self.wave_retries = max(0, int(wave_retries))
        self.retry_backoff_s = float(retry_backoff_s)
        # paged engines: a request may be preempted by recompute (its KV
        # blocks reclaimed under pool pressure, the request requeued
        # with prompt + generated tokens) at most this many times before
        # it resolves "error" — preemption must converge, not livelock
        self.max_preemptions = max(0, int(max_preemptions))
        # consecutive DISTINCT-request prefill failures tolerated before
        # concluding the fault is the engine's, not the requests' (e.g. a
        # raise from inside the compiled prefill after the donated cache
        # was consumed fails every admission thereafter) — reaching it
        # degrades instead of failing requests one-by-one forever while
        # /healthz keeps saying "ok"
        self.prefill_fail_limit = (engine.num_slots + self.wave_retries
                                   if prefill_fail_limit is None
                                   else max(1, int(prefill_fail_limit)))
        self._prefill_fail_streak = 0
        self._queue = collections.deque()
        self._lock = threading.Lock()        # queue + lifecycle flags
        self._wave_lock = threading.Lock()   # one step() at a time
        self._slot_req = [None] * engine.num_slots
        self._draining = False
        self._degraded = False
        self.last_error = None
        self.metrics = ServingMetrics(engine.num_slots)
        # /healthz carries this scheduler's queue depth (fleet routers
        # and LBs read load + pool pressure from one endpoint)
        engine.attach_queue_probe(self.queue_depth)
        pool = getattr(engine, "block_pool", None)
        if pool is not None:
            # seed the prefix-delta baseline with the pool's totals
            # BEFORE any round of ours — the snapshot then reports
            # exactly this scheduler's lookups, first round included
            self.metrics.on_prefix_totals(pool.prefix_hits,
                                          pool.prefix_misses)
        # bounded: callers hold their own Request handles (submit returns
        # them); this ring is a debugging/inspection tail, and unbounded
        # growth would leak every prompt ever served on a long-running
        # server. completed_log=None keeps everything (tests/benches).
        self.completed = collections.deque(maxlen=completed_log)
        # black-box journal coordinates (serving/blackbox.py): the
        # scheduling-round counter stamps every journaled decision so
        # replay can re-submit and re-fault in the same round order;
        # the wave counter names waves in `wave` events
        self._round = 0
        self._wave_seq = 0

    def _replica_ord(self):
        """This scheduler's fleet replica id for journal events (the
        chrome-trace pid is replica_id + 1; None = single-engine)."""
        return self.trace_pid - 1 if self.trace_pid else None

    # ------------------------------------------------------ observability
    def attach_timeseries(self, sampler=None, alerts=None):
        """Attach the metrics-history sampler and/or an AlertManager
        (utils/timeseries, utils/anomaly): both run once per WORKING
        round at wave end, and the alert state rides /healthz next to
        the SLO verdict (one merged health probe — the engine's probe
        slot is newest-wins, so separate attaches would shadow each
        other).  Returns self for chaining."""
        if sampler is not None:
            self._sampler = sampler
        if alerts is not None:
            self._alerts = alerts
        self.engine.attach_health_probe(self._health_extras)
        return self

    def _health_extras(self):
        """The merged /healthz fragment: SLO verdict + alert state."""
        out = {}
        if self.slo_engine is not None:
            out.update(self.slo_engine.health() or {})
        if self._alerts is not None:
            out.update(self._alerts.health() or {})
        return out

    # ---------------------------------------------------------- admission
    def submit(self, request=None, **kw):
        """Enqueue a Request (or build one from kwargs: prompt,
        max_tokens, eos_token_id, timeout, on_token, do_sample,
        temperature). Oversized prompts are rejected CLEANLY here — the
        request is marked REJECTED, a ValueError raises to the caller,
        and the engine/queue state is untouched."""
        if request is None:
            request = Request(**kw)
        # role defense-in-depth: the fleet router filters candidates by
        # role before dispatch, so these raise only on a direct misuse —
        # without finalizing the request (the caller may route it to a
        # capable replica instead)
        if self.role == "decode" and request.handoff is None:
            raise ValueError(
                "decode-role replica accepts only block-level handoff "
                "continuations (this request still needs prefill)")
        if self.role == "prefill" and request.handoff is not None:
            raise ValueError(
                "prefill-role replica cannot import a handoff payload")
        # seed provenance: stamp the engine's PRNG-chain seed on the
        # request (greedy too — the chain is shared) so the journal
        # names the seed that replays it; an already-stamped seed (a
        # fleet hop's continuation) wins
        if request.seed is None:
            request.seed = getattr(self.engine, "seed", None)
        bb = blackbox.get_recorder()
        why = self.engine.validate_prompt(request.prompt)
        if why is not None:
            self.metrics.on_reject()
            if bb is not None:
                bb.admission(request.request_id, verdict="rejected",
                             reason="invalid_prompt",
                             tenant=request.tenant,
                             trace_id=request.trace_id,
                             round=self._round,
                             replica=self._replica_ord())
            request._reject(why)           # raises ValueError
        with self._lock:
            if self._degraded:
                shed = f"engine degraded ({self.last_error})"
            elif self._draining:
                shed = "engine draining (graceful shutdown)"
            elif self.max_queue is not None and len(self._queue) >= \
                    self.max_queue:
                shed = f"queue full (max_queue={self.max_queue})"
            else:
                shed = None
                request.trace_pid = self.trace_pid
                request._mark_submitted()
                self._queue.append(request)
                depth = len(self._queue)
        if shed is not None:
            self.metrics.on_reject()
            if bb is not None:
                bb.admission(request.request_id, verdict="shed",
                             reason=shed, tenant=request.tenant,
                             trace_id=request.trace_id,
                             round=self._round,
                             replica=self._replica_ord())
            request._reject(shed)          # raises ValueError
        if bb is not None:
            bb.submit(request, round=self._round,
                      replica=self._replica_ord())
        self.metrics.on_submit()
        self.metrics.on_queue_depth(depth)
        return request

    def queue_depth(self):
        with self._lock:
            return len(self._queue)

    def _pop_next(self):
        """Next request to admit: strict FCFS — except under block-pool
        pressure with a QoS manager attached, where the pick is
        weighted-fair across tenants (least weighted in-flight cost
        first, FCFS within a tenant) so one saturating tenant cannot
        monopolize every freed block while others queue behind it."""
        with self._lock:
            req, i = None, 0
            if self._queue:
                if self.qos is not None and len(self._queue) > 1 and \
                        self.qos.under_pressure(
                            getattr(self.engine, "block_pool", None)):
                    counts = {}
                    for r in self._slot_req:
                        if r is not None:
                            t = getattr(r, "tenant", "default")
                            counts[t] = counts.get(t, 0) + 1
                    i = self.qos.pick_admission(self._queue, counts)
                req = self._queue[i]
                del self._queue[i]
            depth = len(self._queue)
        self.metrics.on_queue_depth(depth)
        return req

    def _requeue_front(self, req):
        """Put a request back at the queue HEAD (capacity pressure:
        pool-exhausted admission, preemption) — it keeps its FCFS
        standing."""
        with self._lock:
            self._queue.appendleft(req)
            depth = len(self._queue)
        self.metrics.on_queue_depth(depth)

    def _continuation(self, req):
        """The token prefix a (re-)admission must prefill: the prompt
        plus anything already generated — a preempted request resumes by
        recompute, and its next prefill's frontier logits produce the
        NEXT token, not a repeat."""
        return req.prompt + req.output_tokens

    def _combined_bias(self, req):
        """The slot's effective [V] bias row: static logit_bias plus the
        request's token_mask evaluated against what it has emitted so
        far (bool masks normalize to 0/-1e9 in the engine)."""
        bias = self.engine._normalize_bias(req.logit_bias)
        if req.token_mask is not None:
            bias = bias + self.engine._normalize_bias(req.token_mask(req))
        return bias

    def _admission_bias(self, req):
        """Bias row handed to begin_prefill: the first token must obey
        the mask too. A raising token_mask lands inside the admission
        fault barrier — it fails ITS request, nothing else."""
        return (req.logit_bias if req.token_mask is None
                else self._combined_bias(req))

    def _refresh_token_masks(self):
        """Re-evaluate every dynamic token_mask against the tokens its
        request has emitted (constrained decoding advances per token)
        and upload the fresh bias rows before the wave. A raising mask
        callable fails only its own request — same isolation contract
        as on_token callbacks."""
        for slot, req in enumerate(self._slot_req):
            if req is None or req.token_mask is None or \
                    not self.engine.slot_active[slot]:
                continue
            try:
                self.engine.set_slot_bias(slot, self._combined_bias(req))
            except Exception as e:   # noqa: BLE001 — client code
                self.last_error = e
                self.engine.retire_slot(slot)
                self._slot_req[slot] = None
                self._fault("token_mask_error", action="request_failed",
                            request=req, slot=slot, error=e)
                req._fail(e)
                self._complete(req)

    def _admit(self):
        """Assign queued requests to free slots and stage their prefill
        (engine.begin_prefill — block allocation on a paged engine); the
        work itself runs in _advance_prefills, so a long chunked prefill
        folds between decode waves. A request whose timeout already
        expired in the queue is retired without spending a prefill on
        it; an exhausted block pool is CAPACITY, not a request fault —
        the head request waits for blocks to free (or is rejected when
        nothing in flight could ever free them)."""
        bb = blackbox.get_recorder()
        rep = self._replica_ord()
        while True:
            free = self.engine.free_slots()
            if not free:
                return
            req = self._pop_next()
            if req is None:
                return
            if req._timed_out():
                req._finish("timeout")
                self._complete(req)
                continue
            slot = free[0]
            handoff = getattr(req, "handoff", None)
            try:
                if handoff is not None:
                    # block-level handoff: import the prefill replica's
                    # populated KV blocks and arm the slot directly —
                    # ZERO prefill-chunk programs run here (the whole
                    # point: a handoff costs bytes, not recompute)
                    self.engine.import_handoff(
                        slot, self._continuation(req), handoff,
                        do_sample=req.do_sample,
                        temperature=req.temperature,
                        top_k=req.top_k, top_p=req.top_p,
                        logit_bias=self._admission_bias(req),
                        dynamic_mask=req.token_mask is not None)
                else:
                    self.engine.begin_prefill(
                        slot, self._continuation(req),
                        do_sample=req.do_sample,
                        temperature=req.temperature,
                        top_k=req.top_k, top_p=req.top_p,
                        logit_bias=self._admission_bias(req),
                        dynamic_mask=req.token_mask is not None)
            except BlockPoolExhausted as e:
                if self.engine.active_slots() or \
                        self.engine.prefilling_slots():
                    # in-flight work will free blocks: wait at the head.
                    # One fault per wait EPISODE — a long decode can
                    # hold the head here for hundreds of rounds, and
                    # per-round records would flood the counters/journal
                    if not req._cache_waiting:
                        req._cache_waiting = True
                        self._fault("cache_exhausted", action="requeued",
                                    request=req, error=e)
                        if bb is not None:
                            bb.admission(req.request_id,
                                         verdict="deferred",
                                         reason="cache_exhausted",
                                         tenant=req.tenant,
                                         trace_id=req.trace_id,
                                         round=self._round, replica=rep)
                    self._requeue_front(req)
                    return
                # nothing in flight to free blocks — shed cleanly
                self.metrics.on_reject()
                self._fault("cache_exhausted", action="rejected",
                            request=req, error=e)
                if bb is not None:
                    bb.admission(req.request_id, verdict="rejected",
                                 reason="cache_exhausted",
                                 tenant=req.tenant,
                                 trace_id=req.trace_id,
                                 round=self._round, replica=rep)
                req._reject(f"KV cache exhausted ({e})",
                            raise_error=False)
                self.completed.append(req)
                continue
            except Exception as e:   # noqa: BLE001 — fault barrier:
                # isolate the failing admission to ITS request; staging
                # mutates no device state, so the slot stays free and
                # every other lane is untouched
                self.last_error = e
                if handoff is not None:
                    # a refused handoff (digest/geometry mismatch) is a
                    # REQUEST fault — the payload is unusable, so fail
                    # only this request; it never feeds the engine's
                    # prefill-fail streak (the engine is healthy)
                    self._fault("handoff_refused",
                                action="request_failed", request=req,
                                slot=slot, error=e)
                    req.handoff = None
                    req._fail(e)
                    self._complete(req)
                    continue
                if self._prefill_fault(req, slot):
                    return
                continue
            if bb is not None:
                bb.admission(req.request_id, verdict="admitted",
                             slot=slot, tenant=req.tenant,
                             basis=("handoff" if handoff is not None
                                    else "prefill"),
                             trace_id=req.trace_id,
                             round=self._round, replica=rep)
            # handoff consumed one-shot: a LATER re-admission of this
            # request (preemption, migration) replays from the prefix
            # cache like any other continuation
            req.handoff = None
            req._cache_waiting = False         # wait episode (if any) over
            req._start_prefill(slot)
            # engine-internal progress (per-chunk prefill) correlates
            # to the request's chrome flow through the slot
            self.engine.set_slot_trace(slot, req.trace_id,
                                       self.trace_pid)
            self._slot_req[slot] = req

    def _prefill_fault(self, req, slot):
        """Shared admission/chunk fault barrier: fail ONLY this request,
        free the slot, and escalate to degradation after
        `prefill_fail_limit` consecutive distinct-request failures.
        Returns True when the engine degraded (stop the round)."""
        self.engine.retire_slot(slot)      # frees pending state + blocks
        self._slot_req[slot] = None
        self._prefill_fail_streak += 1
        escalate = self._prefill_fail_streak >= self.prefill_fail_limit
        self._fault("prefill_error",
                    action=("degrade" if escalate else "request_failed"),
                    request=req, slot=slot, error=self.last_error)
        req._fail(self.last_error)
        self._complete(req)
        if escalate:
            self._degrade()
            return True
        return False

    def _advance_prefills(self):
        """Run one prefill step per mid-admission slot (ONE chunk on a
        paged engine; the whole bucket on the dense engine). Slots whose
        prefill completed get their first token and become active for
        this round's decode wave. Returns True when a fault escalated to
        degradation."""
        for slot in self.engine.prefilling_slots():
            req = self._slot_req[slot]
            if req._timed_out():
                # chunked prefill can span many rounds — don't keep
                # burning chunk programs (and finally emit a token) on a
                # request that already expired; same semantics as the
                # queue-pop timeout check
                self.engine.retire_slot(slot)
                self._slot_req[slot] = None
                req._finish("timeout")
                self._complete(req)
                continue
            try:
                with RecordEvent("serving/prefill",
                                 pid=self.trace_pid) as ev:
                    first = self.engine.prefill_step(slot)
            except Exception as e:   # noqa: BLE001 — fault barrier
                self.last_error = e
                if self._prefill_fault(req, slot):
                    return True
                continue
            finally:
                self.metrics.on_phase("prefill_chunk", ev.elapsed)
            self._prefill_fail_streak = 0
            if first is None:
                continue             # mid-prefill: decode waves go on
            self.metrics.on_prefill()
            # prev_t is non-None only for a preempted-then-resumed
            # request: its re-prefill token IS an inter-token gap (the
            # preemption stall is real TPOT the client observed)
            prev_t = req.last_token_time
            req._emit(first)
            self.metrics.on_token(time.monotonic(), prev_t=prev_t)
            self._maybe_retire(slot, first)
            if self.role == "prefill" and self._slot_req[slot] is not None:
                # prefill-role epilogue: this replica never decodes —
                # package the populated KV blocks for a decode replica
                self._export_handoff(slot)
        return False

    def _export_handoff(self, slot):
        """Export the slot's populated KV blocks (the prefill just
        completed and emitted its first token) and park (request,
        payload) for the router to hand to a decode replica; the slot
        retires either way — freed blocks keep their prefix hashes, so
        a failed export's fallback (migration-by-recompute, payload
        None) still re-prefills mostly from cache."""
        req = self._slot_req[slot]
        payload = None
        try:
            payload = self.engine.export_slot_kv(slot)
        except Exception as e:   # noqa: BLE001 — fault barrier: the
            # router falls back to recompute, bounded by its budget
            self.last_error = e
            self._fault("handoff_error", action="export_failed",
                        request=req, slot=slot, error=e)
        self.engine.retire_slot(slot)
        self._slot_req[slot] = None
        with self._lock:
            self._handoff_ready.append((req, payload))

    def take_handoffs(self):
        """Drain the prefill-role staging area: [(request, payload)]
        pairs whose prefill completed (payload None = export failed;
        the caller migrates by recompute instead)."""
        with self._lock:
            out = self._handoff_ready
            self._handoff_ready = []
        return out

    # ---------------------------------------------------------- wave loop
    def _maybe_retire(self, slot, last_token, check_length=True):
        """Retire the slot if its request just finished: EOS (even on the
        very first prefill-produced token), a stop sequence, token
        budget, cache horizon, or wall-clock timeout. check_length=False
        suppresses the horizon check for the NON-final tokens of a
        speculative batch: slot_pos is already advanced for the whole
        batch, and only its last token is the one written at the
        horizon — retiring on an earlier one would drop tokens the
        plain engine delivers."""
        req = self._slot_req[slot]
        reason = None
        if req.eos_token_id is not None and last_token == req.eos_token_id:
            reason = "eos"
        elif req.stop_sequences and req._hit_stop():
            reason = "stop"
        elif len(req.output_tokens) >= req.max_tokens:
            reason = "max_tokens"
        elif check_length and self.engine.slot_full(slot):
            reason = "length"
        elif req._timed_out():
            reason = "timeout"
        if reason is not None:
            self.engine.retire_slot(slot)
            self._slot_req[slot] = None
            req._finish(reason)
            self._complete(req)

    def _complete(self, req):
        self.completed.append(req)
        bb = blackbox.get_recorder()
        if bb is not None:
            bb.complete(req, round=self._round,
                        replica=self._replica_ord())
        self.metrics.on_complete(req)
        if self.slo_engine is not None:
            self.slo_engine.observe_request(req)

    def _fault(self, kind, action=None, request=None, slot=None,
               error=None):
        """One fault handled: count it (serving_faults_total{kind}) and
        journal it through the current flight recorder."""
        self.metrics.on_fault(kind)
        rec = flight_recorder.get_recorder()
        if rec is not None:
            rec.fault(kind=kind, action=action,
                      request_id=None if request is None
                      else request.request_id,
                      slot=slot,
                      error=None if error is None else repr(error))

    def _run_wave_with_retry(self):
        """The decode wave behind a bounded-exponential-backoff retry.
        Returns the wave's {slot: token} dict, or None after degrading
        (budget exhausted). The engine raises BEFORE consuming its key
        or the donated cache, so a retried wave replays exactly; an
        error from inside the compiled call may have invalidated the
        donated cache, in which case the retry fails too and the budget
        runs out — degradation, not an infinite loop."""
        delay = self.retry_backoff_s
        for attempt in range(self.wave_retries + 1):
            try:
                with RecordEvent("serving/decode_wave",
                                 pid=self.trace_pid) as ev:
                    toks = self.engine.decode_wave()
                self.last_wave_s = ev.elapsed
                self.metrics.on_phase("decode_wave", ev.elapsed)
                return toks
            except Exception as e:   # noqa: BLE001 — fault barrier
                self.last_error = e
                self._fault("wave_error",
                            action=("retry" if attempt < self.wave_retries
                                    else "degrade"),
                            error=e)
                if attempt >= self.wave_retries:
                    break
                self.metrics.on_wave_retry()
                time.sleep(delay)
                delay *= 2
        self._degrade()
        return None

    def _degrade(self):
        """Graceful degradation: the wave loop cannot make progress, so
        resolve everything cleanly — in-flight requests finish with
        "error", queued requests shed with "rejected", new submits are
        rejected, and /healthz reports "degraded" — instead of leaking
        a stack trace through step()."""
        with self._lock:
            # flag + health transition under ONE lock: a concurrent
            # drain() cannot interleave and overwrite "degraded" with
            # "draining" on an engine that can no longer make progress
            self._degraded = True
            self.engine.set_health_state("degraded")
        self._fault("degraded", action="drain_and_reject",
                    error=self.last_error)
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            self.engine.retire_slot(slot)
            self._slot_req[slot] = None
            req._fail(f"engine degraded: {self.last_error!r}")
            self._complete(req)
        with self._lock:
            parked = [req for req, _ in self._handoff_ready]
            self._handoff_ready = []
        for req in parked:
            req._fail(f"engine degraded: {self.last_error!r}")
            self._complete(req)
        while True:
            req = self._pop_next()
            if req is None:
                break
            self.metrics.on_reject()
            req._reject(f"engine degraded ({self.last_error!r})",
                        raise_error=False)
            # shed, not completed: on_complete would double-count the
            # request and pollute the latency histogram with a
            # queue-wait-only sample — the inspection ring still gets it
            self.completed.append(req)

    def evacuate(self):
        """Pull every accepted-but-unresolved request out of this
        scheduler WITHOUT resolving it, and stop accepting work. The
        fleet failover path calls this on a replica presumed DEAD, so
        no engine call is made here. The router migrates from its OWN
        live-request registry (serving/fleet/router.py scans _live —
        it must not trust a dead replica's bookkeeping); the returned
        list (in-slot first, then queued) is informational: operators
        and tests can see exactly what a kill stranded."""
        with self._wave_lock:          # never mid-round: whole rounds
            with self._lock:           # interleave with the evacuation
                self._degraded = True  # step() idles; submit() sheds
                if self.last_error is None:
                    self.last_error = "replica evacuated"
                queued = list(self._queue)
                self._queue.clear()
                # handoffs parked but never picked up (the payload dies
                # with the replica; the request migrates by recompute)
                parked = [req for req, _ in self._handoff_ready]
                self._handoff_ready = []
            out = [req for req in self._slot_req if req is not None]
            self._slot_req = [None] * self.engine.num_slots
            out.extend(parked)
            out.extend(queued)
        self.metrics.on_queue_depth(0)
        return out

    def step(self):
        """One scheduling round: refill free slots from the queue, run
        one batched decode wave, stream the tokens, retire finished
        slots. Returns the number of requests still in flight or queued.

        Serialized by `_wave_lock`, so concurrent drivers (a run() loop
        in one thread, shutdown() in another) interleave whole rounds
        instead of racing the engine's donated caches."""
        with self._wave_lock:
            return self._step_locked()

    def _record_spec_wave(self, waved):
        """Speculative-wave accounting: proposed/accepted counters +
        acceptance-rate gauge (serving_spec_* — docs/observability.md),
        a `spec` journal event, and a per-wave trace instant carrying
        the wave's spec_depth (accepted tokens per dispatched lane)."""
        proposed = getattr(self.engine, "last_spec_proposed", None)
        if proposed is None:
            return                      # not a speculative engine
        accepted = self.engine.last_spec_accepted
        self.metrics.on_spec(proposed, accepted)
        depth = accepted / waved if waved else 0.0
        rec = flight_recorder.get_recorder()
        if rec is not None:
            rec.spec(proposed=proposed, accepted=accepted,
                     lanes=waved, spec_depth=round(depth, 4))
        if profiler.trace_enabled():
            telemetry.trace_instant(
                0, "SPEC_WAVE", pid=self.trace_pid,
                spec_depth=round(depth, 4), proposed=proposed,
                accepted=accepted)

    def _preemption_victim(self, starved_slot):
        """Priority preemption: choose which lane recompute evicts to
        unblock a starved one. Among the OTHER active lanes, pick the
        lowest-priority one STRICTLY below the starved request's
        priority (ties: latest-submitted goes, preserving FCFS within a
        class). None when no lane ranks below — then the starved lane
        itself is evicted, which at uniform priority (the default 0
        everywhere) reproduces pre-QoS behavior exactly."""
        starved_pri = getattr(self._slot_req[starved_slot], "priority", 0)
        victim = None
        for slot, req in enumerate(self._slot_req):
            if req is None or slot == starved_slot or \
                    not self.engine.slot_active[slot]:
                continue
            pri = getattr(req, "priority", 0)
            if pri >= starved_pri:
                continue
            if victim is None:
                victim = slot
                continue
            vreq = self._slot_req[victim]
            vpri = getattr(vreq, "priority", 0)
            if pri < vpri or (pri == vpri and (req.submit_time or 0) >
                              (vreq.submit_time or 0)):
                victim = slot
        return victim

    def _evict_for_recompute(self, slot, victim_for=None):
        """Preemption-by-recompute of one lane: free the slot's blocks,
        requeue the request with prompt + generated tokens (the freed
        blocks' prefix hashes make the re-prefill mostly cache hits). A
        request past its preemption budget, or one whose continuation
        could never fit the pool, resolves "error" instead of
        livelocking. `victim_for` names the starved request this
        eviction unblocks (priority preemption) for the journal."""
        req = self._slot_req[slot]
        bb = blackbox.get_recorder()
        self.engine.retire_slot(slot)          # frees the blocks
        self._slot_req[slot] = None
        req.preemptions += 1
        cont = self._continuation(req)
        why = self.engine.validate_prompt(cont)
        if req.preemptions > self.max_preemptions or why is not None:
            self._fault("cache_exhausted", action="request_failed",
                        request=req, slot=slot)
            if bb is not None:
                bb.preempt(req.request_id, slot=slot,
                           reason="budget_spent", victim_for=victim_for,
                           preemptions=req.preemptions,
                           round=self._round,
                           replica=self._replica_ord())
            req._fail(why or "KV cache exhausted: preemption budget "
                             f"spent ({req.preemptions}x)")
            self._complete(req)
            return
        self._fault("cache_exhausted", action="preempted",
                    request=req, slot=slot)
        if bb is not None:
            bb.preempt(req.request_id, slot=slot, reason="pool_pressure",
                       victim_for=victim_for,
                       preemptions=req.preemptions, round=self._round,
                       replica=self._replica_ord())
        self._requeue_front(req)

    def _preempt_starved(self):
        """Pool-exhausted lanes (the wave excluded them): evict a lane
        by recompute so blocks free up. Which lane is a QoS decision —
        a lower-priority lane below the starved request goes first
        (_preemption_victim); otherwise the starved lane evicts itself
        (and the victim path leaves it armed to retry allocation at the
        next wave against the freed blocks)."""
        for slot in self.engine.last_starved_slots:
            if self._slot_req[slot] is None:
                continue     # already evicted as another lane's victim
                             # (or finished during this round's dispatch)
            victim = self._preemption_victim(slot)
            if victim is None:
                self._evict_for_recompute(slot)
            else:
                self._evict_for_recompute(
                    victim,
                    victim_for=self._slot_req[slot].request_id)

    def _step_locked(self):
        if self._degraded:
            return 0
        # round stamp for every decision journaled below: replay
        # re-submits and re-faults in the same round order, so the
        # counter must tick before ANY of this round's decisions
        self._round += 1
        with RecordEvent("serving/admission", pid=self.trace_pid) as ev:
            self._admit()
        self.metrics.on_phase("admission", ev.elapsed)
        # captured BEFORE the advance: a prefill that admits, emits its
        # first token, and retires within one round still counts as a
        # working round for the pool sample below
        prefilled = bool(self.engine.prefilling_slots())
        if self._advance_prefills():
            return 0                         # degraded mid-advance
        self._refresh_token_masks()
        active = self.engine.active_slots()
        if active:
            toks = self._run_wave_with_retry()
            if toks is None:                 # degraded: everything is
                return 0                     # resolved, nothing pending
            waved = len(active) - len(self.engine.last_starved_slots)
            if waved > 0:     # all-starved rounds dispatch no program —
                self.metrics.on_wave(  # don't count phantom waves
                    waved, wave_s=self.last_wave_s,
                    flops=self._wave_cost.get("flops"),
                    bytes_accessed=self._wave_cost.get("bytes_accessed"))
                self._record_spec_wave(waved)
            bb = blackbox.get_recorder()
            if bb is not None and toks:
                # membership captured from `toks` BEFORE the dispatch
                # loop below retires finished slots (after it, the
                # slot->request map may already be cleared)
                self._wave_seq += 1
                bb.wave(
                    self._wave_seq,
                    members=[{"slot": s,
                              "request_id": self._slot_req[s].request_id,
                              "tokens": (len(t) if isinstance(t, list)
                                         else 1)}
                             for s, t in sorted(toks.items())
                             if self._slot_req[s] is not None],
                    starved=sorted(self.engine.last_starved_slots)
                    or None,
                    nonfinite=sorted(self.engine.last_nonfinite_slots)
                    or None,
                    spec_proposed=getattr(self.engine,
                                          "last_spec_proposed", None),
                    spec_accepted=getattr(self.engine,
                                          "last_spec_accepted", None),
                    round=self._round, replica=self._replica_ord())
            # fused-sentinel fallout: retire ONLY the poisoned lanes —
            # their requests resolve with "error", healthy neighbours
            # stream on token-identically (proven in chaos_serving)
            for slot in self.engine.last_nonfinite_slots:
                req = self._slot_req[slot]
                self.engine.retire_slot(slot)
                self._slot_req[slot] = None
                self._fault("nonfinite", action="slot_retired",
                            request=req, slot=slot)
                req._fail("non-finite logits in decode wave")
                self._complete(req)
            now = time.monotonic()
            with RecordEvent("serving/host_dispatch",
                             pid=self.trace_pid) as ev:
                for slot, emitted in toks.items():
                    req = self._slot_req[slot]
                    # a speculative wave emits a BATCH per lane; stream
                    # it in order and stop at the first retirement
                    # (eos/stop/budget/horizon) — the batch's rejected
                    # tail past that point is dropped, exactly what the
                    # non-speculative wave would never have generated
                    if not isinstance(emitted, list):
                        emitted = [emitted]
                    for j, tok in enumerate(emitted):
                        prev_t = req.last_token_time
                        req._emit(tok)
                        self.metrics.on_token(now, prev_t=prev_t)
                        self._maybe_retire(
                            slot, tok, check_length=j == len(emitted) - 1)
                        if self._slot_req[slot] is None:
                            break
            self.metrics.on_phase("host_dispatch", ev.elapsed)
            # AFTER the dispatch loop: a priority victim was in this
            # wave — evicting it first would drop the token it just
            # produced (starved lanes were never in `toks`, so they
            # don't care about the ordering)
            self._preempt_starved()
        pool = getattr(self.engine, "block_pool", None)
        if pool is not None and (active or prefilled):
            # pool sample per WORKING round (idle spins don't dilute the
            # integral — same cadence discipline as on_wave's slot
            # occupancy): utilization + prefix tallies ride the snapshot
            self.metrics.on_blocks(pool.used, pool.usable)
            self.metrics.on_prefix_totals(pool.prefix_hits,
                                          pool.prefix_misses)
        if self.slo_engine is not None and (active or prefilled):
            # re-evaluate once per WORKING round: gauges track live,
            # transitions journal, /healthz serves the cached verdict
            self.slo_engine.evaluate()
        if active or prefilled:
            # the history sampler and the anomaly detectors run on the
            # same working-round cadence (idle spins sample nothing:
            # they would flood the ladders with flat lines and dilute
            # every EWMA baseline toward the idle value)
            if self._sampler is not None:
                self._sampler.maybe_sample()
            if self._alerts is not None:
                self._alerts.evaluate()
        # chrome-trace counter track: occupancy/queue depth over time,
        # on the same timeline as the decode-wave slices
        if profiler.trace_enabled():
            profiler.emit_trace_event({
                "ph": "C", "name": "serving/slots", "cat": "serving",
                "pid": self.trace_pid,
                "args": {"active": self.in_flight(),
                         "queued": self.queue_depth()}})
        return self.in_flight() + self.queue_depth()

    def in_flight(self):
        return sum(1 for r in self._slot_req if r is not None)

    @property
    def draining(self):
        return self._draining

    @property
    def degraded(self):
        return self._degraded

    # ------------------------------------------------------- graceful stop
    def drain(self):
        """Stop admitting new work: requests already accepted (queued or
        in a slot) run to completion; new submit()s are shed with
        finish_reason "rejected". /healthz reports "draining". Keep
        driving step()/run() until it returns 0 to finish the accepted
        work."""
        with self._lock:
            self._draining = True
            if not self._degraded:     # degraded is sticky: see _degrade
                self.engine.set_health_state("draining")

    def shutdown(self, max_waves=None):
        """Graceful shutdown: drain(), drive the wave loop until every
        accepted request resolves, then stop the engine's metrics
        exporter. Returns the number of waves run. Safe alongside a
        concurrent run()/step() driver — rounds serialize on
        `_wave_lock`, so the two loops cooperate on draining rather
        than racing the engine."""
        self.drain()
        waves = self.run(max_waves=max_waves)
        self.engine.stop_metrics_server()
        return waves

    def run(self, drain=True, max_waves=None):
        """Drive step() until the queue and all slots drain (or max_waves
        hit). Producer threads may keep submit()ing while this runs."""
        waves = 0
        while self.step():
            waves += 1
            if max_waves is not None and waves >= max_waves:
                break
        return waves

    # ---------------------------------------------------------- conveniences
    def generate(self, prompt, **kw):
        """Blocking single-request convenience (the create_llm_predictor
        surface): submit, drain, return the generated token list."""
        req = self.submit(prompt=prompt, **kw)
        while not req.done:
            self.step()
        return req.output_tokens
