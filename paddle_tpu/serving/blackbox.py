"""Serving black-box recorder: a bounded journal of every
replay-relevant serving decision, with deterministic incident replay.

The serving-side counterpart of `utils/flight_recorder.py` (same
ring-buffered JSONL journal, crash-flush context manager, and
module-level `set_recorder`/`get_recorder` no-plumbing pattern), but
where the flight recorder journals *faults*, the black box journals
*decisions*: request submission (prompt tokens + digest, sampling
params, resolved seed, tenant/priority), QoS admission verdicts, wave
membership, preemption/eviction, fleet hops (dispatch / migrate /
handoff / kv export-import / replica spawn-retire), and completion
(output-token digest plus per-phase wall timings).

The repo's serving stack is token-exact reproducible end to end
(failover, migration, disagg handoff, and spec decoding are all proven
bitwise), so capturing the externally-sourced decision inputs makes a
run *replayable*: `scripts/replay_incident.py` rebuilds a fresh
engine/fleet from the journal's `run_start` harness metadata, re-submits
the window in order, re-forces the recorded replica kills, and verifies
outputs token-exact against the recorded digests.

Determinism contract: wall-clock state lives only in the stamped `ts`
field and the explicit `wall` sidecar of `complete` events, and the
only per-run randomness is `run_id`. `replay_view(events)` strips those
and normalizes process-lifetime request/trace ids (the global `Request`
counter keeps counting across runs in one process) to journal ordinals,
so two runs that made identical decisions produce **byte-identical**
views: `json.dumps(replay_view(evs), sort_keys=True)` is a fitness
hash for the whole serving stack.

Zero-overhead discipline: every emission site in the serving stack is
gated on `blackbox.get_recorder() is not None` — recording detached
costs one module-global read per site, nothing else.
"""
import collections
import contextlib
import hashlib
import json
import os
import threading
import time
import uuid

from ..utils import telemetry
from ..utils.flight_recorder import _json_safe

#: journal event taxonomy (the `ev` field). ptlint's
#: `event-kind-documented` rule checks emission call sites against the
#: kind tuples in this module and docs/observability.md.
EVENT_KINDS = (
    "run_start",   # run bracketing: run id, recorder meta, harness config
    "submit",      # request accepted: prompt tokens+digest, sampling, seed
    "admission",   # QoS/scheduler verdict: picked/admitted/deferred/shed/rejected
    "wave",        # decode wave membership: slots, tokens, spec counts
    "preempt",     # eviction for recompute: victim, reason, budget
    "hop",         # fleet-plane movement, see HOP_KINDS
    "complete",    # request finished: output digest, wall sidecar
    "incident",    # incident bundle snapshotted (alert latched firing)
    "run_end",     # run bracketing: status + drop counters
)

#: `hop` event sub-kinds (the `kind` field of `ev == "hop"` events).
HOP_KINDS = (
    "dispatch",        # request placed on a replica
    "migrate",         # live migration off a dead/dying replica
    "handoff",         # prefill->decode KV handoff (disagg fleet)
    "kv_export",       # engine exported a slot's KV blocks (digested)
    "kv_import",       # engine imported a KV payload (digest verified)
    "replica_spawn",   # replica (re)joined the rotation
    "replica_retire",  # replica left the rotation (killed/degraded)
)

#: stamped / sidecar fields excluded from the replay-relevant payload
REPLAY_EXCLUDED = ("ts", "wall", "run_id")

# Fields holding process-lifetime identifiers, normalized to journal
# ordinals by replay_view (two identical runs in one process draw
# different ids from the global Request/FleetRequest counters).
_REQ_ID_FIELDS = ("request_id", "local_request_id", "victim_for")
_TRACE_ID_FIELDS = ("trace_id",)


def token_digest(tokens):
    """Content digest of a token stream (sha256 prefix, 16 hex chars).

    The journal records digests; replay verifies regenerated streams
    against them. Canonical form is the comma-joined decimal ints, so
    the digest is independent of container/int dtype.
    """
    raw = ",".join(str(int(t)) for t in tokens)
    return hashlib.sha256(raw.encode("utf-8")).hexdigest()[:16]


class BlackBoxRecorder:
    """Ring-buffered JSONL journal of serving decisions.

    Mirrors `utils.flight_recorder.FlightRecorder`: events are held in
    bounded deques (`ring_size`), flushed to `path` in batches of
    `flush_every`, and crash-flushed by ``__exit__``. Two additions:

    - `clock`: injectable time source (tests pin it to a constant so
      two runs' journals are byte-comparable even before
      `replay_view` stripping).
    - `bundle_dir`: when set, `incident_bundle()` snapshots the ring
      tail + `telemetry.snapshot_history()` + a manifest into a
      self-contained per-incident directory (`AlertManager` calls it
      when an alert latches firing).
    """

    def __init__(self, path=None, ring_size=512, flush_every=1,
                 meta=None, clock=time.time, bundle_dir=None):
        self.path = path
        self.ring_size = int(ring_size)
        self.flush_every = max(1, int(flush_every))
        self.meta = dict(meta or {})
        self.bundle_dir = bundle_dir
        self._clock = clock
        self._lock = threading.Lock()
        self._pending = collections.deque(maxlen=self.ring_size)
        self._recent = collections.deque(maxlen=self.ring_size)
        self._dropped = 0
        self._seq = 0
        self._bundle_seq = 0
        self._counts = collections.Counter()
        self._file = None
        self._run_id = None
        self._run_start_fields = None
        self._prev = _MISSING
        self._closed = False

    # ------------------------------------------------------------------
    # core record/flush (flight-recorder pattern)
    # ------------------------------------------------------------------

    def record(self, event, **fields):
        """Append one event. `event` names the kind (the `ev` field);
        extra fields are JSON-sanitised. Returns the stamped dict."""
        ev = {"ev": event, "ts": round(float(self._clock()), 6)}
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            for k, v in fields.items():
                ev[k] = _json_safe(v)
            self._counts[event] += 1
            if len(self._pending) == self._pending.maxlen:
                self._dropped += 1
            self._pending.append(ev)
            self._recent.append(ev)
            should_flush = (self.path is not None
                            and len(self._pending) >= self.flush_every)
        if should_flush:
            self.flush()
        return ev

    def flush(self):
        """Write pending events to the journal file (append mode)."""
        if self.path is None:
            with self._lock:
                self._pending.clear()
            return
        with self._lock:
            batch = list(self._pending)
            self._pending.clear()
            if not batch:
                return
            if self._file is None:
                self._file = open(self.path, "a", encoding="utf-8")
            for ev in batch:
                self._file.write(json.dumps(ev, allow_nan=False) + "\n")
            self._file.flush()

    def close(self):
        self.flush()
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            self._closed = True

    def events(self):
        """Most recent events (ring tail), oldest first."""
        with self._lock:
            return list(self._recent)

    def counts(self):
        with self._lock:
            return dict(self._counts)

    @property
    def dropped_events(self):
        with self._lock:
            return self._dropped

    # ------------------------------------------------------------------
    # run bracketing + crash flush
    # ------------------------------------------------------------------

    def run_start(self, harness=None, **fields):
        """Open the run. `harness` carries everything replay needs to
        rebuild the serving stack (model/engine/fleet config) and is
        also copied into incident-bundle manifests. Idempotent."""
        if self._run_id is not None:
            return self._run_id
        self._run_id = uuid.uuid4().hex[:12]
        self._run_start_fields = _json_safe(harness) if harness else None
        self.record("run_start", run_id=self._run_id, meta=self.meta,
                    harness=self._run_start_fields, **fields)
        return self._run_id

    def run_end(self, status="ok", **fields):
        self.record("run_end", status=status, counts=dict(self._counts),
                    dropped_events=self._dropped, **fields)
        self.flush()

    def __enter__(self):
        self._prev = get_recorder()
        set_recorder(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        try:
            if exc_type is not None:
                self.run_end(status="crashed",
                             error=f"{exc_type.__name__}: {exc}")
            elif self._counts.get("run_end", 0) == 0:
                self.run_end(status="ok")
        finally:
            set_recorder(self._prev if self._prev is not _MISSING else None)
            self._prev = _MISSING
            self.close()
        return False

    # ------------------------------------------------------------------
    # typed events
    # ------------------------------------------------------------------

    def submit(self, request, origin="scheduler", round=None,
               replica=None):
        """Request accepted for serving. Records the prompt verbatim
        (replay re-submits it) plus its digest, the full sampling
        config, and the resolved seed."""
        prompt = [int(t) for t in request.prompt]
        self.record(
            "submit", origin=origin,
            request_id=request.request_id,
            trace_id=getattr(request, "trace_id", None),
            tenant=getattr(request, "tenant", "default"),
            priority=getattr(request, "priority", None),
            seed=getattr(request, "seed", None),
            prompt=prompt,
            prompt_sha=token_digest(prompt),
            prompt_len=len(prompt),
            max_tokens=request.max_tokens,
            eos_token_id=getattr(request, "eos_token_id", None),
            sampling={
                "do_sample": bool(getattr(request, "do_sample", False)),
                "temperature": float(getattr(request, "temperature", 1.0)),
                "top_k": int(getattr(request, "top_k", 0) or 0),
                "top_p": float(getattr(request, "top_p", 1.0)),
            },
            stop_sequences=getattr(request, "stop_sequences", None),
            has_logit_bias=getattr(request, "logit_bias", None) is not None,
            has_token_mask=getattr(request, "token_mask", None) is not None,
            handoff=getattr(request, "handoff", None) is not None,
            round=round, replica=replica)

    def admission(self, request_id, verdict, reason=None, slot=None,
                  tenant=None, basis=None, trace_id=None, round=None,
                  replica=None, **extra):
        """QoS/scheduler admission verdict: `picked` (QoS weighted-fair
        selection), `admitted` (slot staged), `deferred` (waiting at
        head), `shed`/`rejected` (refused)."""
        self.record("admission", request_id=request_id, verdict=verdict,
                    reason=reason, slot=slot, tenant=tenant, basis=basis,
                    trace_id=trace_id, round=round, replica=replica,
                    **extra)

    def wave(self, wave_id, members, starved=None, nonfinite=None,
             spec_proposed=None, spec_accepted=None, round=None,
             replica=None):
        """One decode wave: which requests rode it in which slots, how
        many tokens each emitted, and the speculative accept counts."""
        self.record("wave", wave_id=wave_id, members=members,
                    starved=starved, nonfinite=nonfinite,
                    spec_proposed=spec_proposed,
                    spec_accepted=spec_accepted,
                    round=round, replica=replica)

    def preempt(self, request_id, slot, reason, victim_for=None,
                preemptions=None, round=None, replica=None):
        """A request was evicted from its slot for later recompute
        (`pool_pressure`) or failed out (`budget_spent`)."""
        self.record("preempt", request_id=request_id, slot=slot,
                    reason=reason, victim_for=victim_for,
                    preemptions=preemptions, round=round, replica=replica)

    def hop(self, kind, request_id=None, trace_id=None,
            local_request_id=None, src=None, dst=None, round=None,
            **extra):
        """Fleet-plane movement (see HOP_KINDS). `src`/`dst` are
        replica ids; `local_request_id` is the hop-local scheduler
        request id (correlates with that replica's scheduler events)."""
        self.record("hop", kind=kind, request_id=request_id,
                    trace_id=trace_id, local_request_id=local_request_id,
                    src=src, dst=dst, round=round, **extra)

    def complete(self, request, origin="scheduler", round=None,
                 replica=None, migrations=None):
        """Request finished (any finish reason). The output digest is
        what replay verifies against; wall timings live in the `wall`
        sidecar so the replay-relevant payload stays run-deterministic."""
        toks = list(request.output_tokens)
        wall = {}
        for name in ("ttft", "latency", "tpot"):
            v = getattr(request, name, None)
            if v is not None:
                wall[name + "_s"] = round_s(v)
        self.record(
            "complete", origin=origin,
            request_id=request.request_id,
            trace_id=getattr(request, "trace_id", None),
            tenant=getattr(request, "tenant", "default"),
            finish_reason=request.finish_reason,
            error=None if request.error is None else str(request.error),
            n_tokens=len(toks),
            output_sha=token_digest(toks),
            seed=getattr(request, "seed", None),
            migrations=migrations,
            round=round, replica=replica,
            wall=wall or None)

    def incident(self, rule, bundle, severity=None, **detail):
        """An alert latched firing and an incident bundle was written."""
        self.record("incident", rule=rule, severity=severity,
                    bundle=bundle, **detail)

    # ------------------------------------------------------------------
    # incident bundles
    # ------------------------------------------------------------------

    def incident_bundle(self, rule, severity=None, detail=None):
        """Snapshot a self-contained incident bundle directory:

        - ``journal.jsonl``  — the ring tail (last-N journal events)
        - ``history.json``   — `telemetry.snapshot_history()` (the
          sampler's metric time-series, when a sampler is installed)
        - ``manifest.json``  — rule/severity/detail, run id, recorder
          meta + harness config, event counts

        Returns the bundle path, or None when `bundle_dir` is unset.
        """
        if self.bundle_dir is None:
            return None
        with self._lock:
            self._bundle_seq += 1
            n = self._bundle_seq
            tail = list(self._recent)
        dirname = os.path.join(self.bundle_dir, f"incident-{n:03d}-{rule}")
        os.makedirs(dirname, exist_ok=True)
        with open(os.path.join(dirname, "journal.jsonl"), "w",
                  encoding="utf-8") as f:
            for ev in tail:
                f.write(json.dumps(ev, allow_nan=False) + "\n")
        try:
            history = telemetry.snapshot_history()
        except Exception:
            history = None
        with open(os.path.join(dirname, "history.json"), "w",
                  encoding="utf-8") as f:
            json.dump(_json_safe(history), f, sort_keys=True)
        manifest = {
            "version": 1,
            "rule": rule,
            "severity": severity,
            "detail": _json_safe(detail) if detail else None,
            "run_id": self._run_id,
            "meta": self.meta,
            "harness": self._run_start_fields,
            "counts": dict(self._counts),
            "events": len(tail),
        }
        with open(os.path.join(dirname, "manifest.json"), "w",
                  encoding="utf-8") as f:
            json.dump(manifest, f, sort_keys=True, indent=2)
        self.incident(rule=rule, bundle=dirname, severity=severity)
        return dirname


class _Missing:
    def __repr__(self):
        return "<missing>"


_MISSING = _Missing()

_current = None
_current_lock = threading.Lock()


def set_recorder(recorder):
    """Install `recorder` as the process-wide black box (None detaches).
    Returns the previous recorder."""
    global _current
    with _current_lock:
        prev = _current
        _current = recorder
    return prev


def get_recorder():
    """The active recorder, or None. Every serving emission site gates
    on this — detached recording is a single global read."""
    return _current


@contextlib.contextmanager
def recording(recorder):
    """Scope `recorder` as the active black box (crash-flush on exit)."""
    with recorder:
        yield recorder


def read_journal(path):
    """Parse a JSONL journal strictly (raises on malformed lines)."""
    events = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: malformed journal line: {e}") \
                    from None
    return events


def round_s(v, ndigits=6):
    try:
        return round(float(v), ndigits)
    except (TypeError, ValueError):
        return None


# ----------------------------------------------------------------------
# replay-relevant view + per-request traces
# ----------------------------------------------------------------------

def replay_view(events):
    """The replay-relevant payload of a journal: events minus the
    wall-clock fields (`ts`, the `wall` sidecar) and the per-run random
    `run_id`, with process-lifetime request/trace ids normalized to
    first-appearance ordinals. Two runs that made identical decisions
    yield views whose `json.dumps(..., sort_keys=True)` are
    byte-identical — the determinism tests and replay divergence diffs
    both compare exactly that."""
    req_map, trace_map = {}, {}

    def norm(table, v):
        if v is None:
            return None
        if v not in table:
            table[v] = len(table) + 1
        return table[v]

    def walk(obj):
        if isinstance(obj, dict):
            out = {}
            for k, v in obj.items():
                if k in REPLAY_EXCLUDED:
                    continue
                if k in _REQ_ID_FIELDS:
                    out[k] = norm(req_map, v)
                elif k in _TRACE_ID_FIELDS:
                    out[k] = norm(trace_map, v)
                else:
                    out[k] = walk(v)
            return out
        if isinstance(obj, list):
            return [walk(x) for x in obj]
        return obj

    return [walk(ev) for ev in events]


def request_traces(events, limit=None):
    """Group journal events into per-request decision timelines.

    Fleet requests (their hops share a `trace_id`) fold into a single
    trace; hop-local scheduler events are folded in via the dispatch
    hop's `local_request_id`. Returns traces in first-submission order;
    `limit` keeps only the most recent N (what `/debug/requests`
    serves)."""
    traces = {}
    order = []
    rid_to_key = {}      # request_id (incl. hop-local) -> trace key

    def key_for(ev):
        if ev.get("trace_id") is not None:
            return ("t", ev["trace_id"])
        if ev.get("request_id") is not None:
            return ("r", ev["request_id"])
        return None

    def get_trace(key, ev):
        tr = traces.get(key)
        if tr is None:
            tr = traces[key] = {
                "request_id": ev.get("request_id"),
                "trace_id": ev.get("trace_id"),
                "tenant": ev.get("tenant"),
                "seed": ev.get("seed"),
                "events": [],
            }
            order.append(key)
        return tr

    def compact(ev):
        out = {}
        for k, v in ev.items():
            if k in ("ts", "run_id", "prompt", "members"):
                continue
            if v is None:
                continue
            out[k] = v
        return out

    for ev in events:
        name = ev.get("ev")
        if name == "wave":
            # fan wave membership out to each member's trace
            for m in ev.get("members") or ():
                key = rid_to_key.get(m.get("request_id"))
                if key is None or key not in traces:
                    continue
                traces[key]["events"].append({
                    "seq": ev.get("seq"), "ev": "wave",
                    "wave_id": ev.get("wave_id"), "slot": m.get("slot"),
                    "tokens": m.get("tokens"), "round": ev.get("round"),
                    "replica": ev.get("replica"),
                    "spec_proposed": ev.get("spec_proposed"),
                    "spec_accepted": ev.get("spec_accepted"),
                })
            continue
        if name not in ("submit", "admission", "preempt", "hop",
                        "complete"):
            continue
        rid = ev.get("request_id")
        lrid = ev.get("local_request_id")
        if name == "submit":
            key = rid_to_key.get(rid) or key_for(ev)
            if rid is not None:
                rid_to_key[rid] = key
        else:
            key = rid_to_key.get(rid) or key_for(ev)
        if key is None:
            continue
        tr = get_trace(key, ev)
        if lrid is not None:
            rid_to_key[lrid] = key
        if name == "submit":
            for field in ("tenant", "seed"):
                if tr.get(field) is None and ev.get(field) is not None:
                    tr[field] = ev[field]
            # first submit wins: migration/handoff continuation
            # re-submits must not masquerade as the client's prompt
            if tr.get("prompt_len") is None:
                tr["prompt_len"] = ev.get("prompt_len")
                tr["prompt_sha"] = ev.get("prompt_sha")
                tr["sampling"] = ev.get("sampling")
        elif name == "complete":
            tr["finish_reason"] = ev.get("finish_reason")
            tr["n_tokens"] = ev.get("n_tokens")
            tr["output_sha"] = ev.get("output_sha")
            if ev.get("migrations") is not None:
                tr["migrations"] = ev["migrations"]
            if ev.get("wall") is not None:
                tr["wall"] = ev["wall"]
        tr["events"].append(compact(ev))

    out = [traces[k] for k in order]
    if limit is not None and len(out) > limit:
        out = out[-limit:]
    return out


def _debug_requests_payload():
    """`/debug/requests` provider: recent per-request decision traces
    from the active recorder's ring (empty when detached)."""
    rec = get_recorder()
    if rec is None:
        return {"recording": False, "requests": []}
    return {"recording": True,
            "requests": request_traces(rec.events(), limit=32)}


# utils must not import serving; the debug endpoint reaches the black
# box through this provider hook instead.
telemetry.set_debug_requests_provider(_debug_requests_payload)
