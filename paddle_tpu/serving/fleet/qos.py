"""Multi-tenant QoS: per-tenant SLO cohorts, weighted-fair admission,
and preemption priorities for the serving fleet.

One shared fleet serves many tenants, and pre-QoS everything was FCFS:
a tenant saturating the queue under block-pool pressure starves every
other tenant's admissions, and preemption-by-recompute evicts whichever
lane happened to starve — a premium request pays for a bulk tenant's
appetite. This module gives each tenant three levers:

  * **weight** — weighted-fair admission under pool pressure: the
    scheduler picks the queued request whose tenant has the least
    weighted in-flight cost (`in_flight / weight`), FCFS within a
    tenant. Off pressure, admission stays strict FCFS (weights change
    who waits when blocks are scarce, not the happy path).
  * **priority** — preemption rank: a starved lane evicts the lowest-
    priority active lane STRICTLY below it (scheduler
    `_preemption_victim`) instead of always evicting itself, so bulk
    work absorbs the recompute cost of pressure it created.
  * **slo** — an `SLOPolicy` per latency tier: each tenant with a
    policy gets its own burn-rate window (serving/slo.py), published as
    `serving_tenant_*` gauges labeled by tenant, so "the premium tier
    is in SLO" is a first-class, per-cohort verdict instead of a
    fleet-wide average that a noisy neighbour can hide inside.

The manager is duck-typed into the Scheduler (under_pressure /
pick_admission) and driven by the disaggregated router (observe /
evaluate) — a fleet without one behaves exactly as before (tenant
"default", priority 0, FCFS).
"""
import threading

from ...utils import flight_recorder, telemetry
from .. import blackbox
from ..slo import SLOEngine, SLOPolicy

_TENANT_ATTAINMENT = telemetry.gauge(
    "serving_tenant_attainment",
    "Per-tenant SLO attainment over the sliding window (1.0 = every "
    "request of this tenant met its cohort's targets)",
    labelnames=("tenant",))
_TENANT_BURN = telemetry.gauge(
    "serving_tenant_burn_rate",
    "Per-tenant error-budget burn rate (1.0 = burning exactly the "
    "cohort's budget; see serving/slo.py)",
    labelnames=("tenant",))
_TENANT_REQUESTS = telemetry.counter(
    "serving_tenant_requests_total",
    "Requests finalized per tenant cohort (every finish reason)",
    labelnames=("tenant",))

#: the implicit cohort every request without a tenant bills against
DEFAULT_TENANT = "default"


class Tenant:
    """One tenant cohort: a name, a fair-share weight (> 0), a
    preemption priority (higher survives longer under pool pressure),
    and optionally its own SLOPolicy (latency tier)."""

    def __init__(self, name, weight=1.0, priority=0, slo=None):
        self.name = str(name)
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0, "
                             f"got {weight}")
        self.priority = int(priority)
        if slo is not None and not isinstance(slo, SLOPolicy):
            raise TypeError(f"tenant {name!r}: slo must be an SLOPolicy")
        self.slo = slo

    def describe(self):
        d = {"name": self.name, "weight": self.weight,
             "priority": self.priority}
        if self.slo is not None:
            d["slo"] = self.slo.describe()
        return d

    def __repr__(self):
        return (f"Tenant({self.name!r}, weight={self.weight}, "
                f"priority={self.priority}, "
                f"slo={'yes' if self.slo else 'no'})")


class QoSManager:
    """The fleet's tenant registry + per-tenant SLO windows.

    tenants: iterable of Tenant. A "default" tenant is implied (weight
        1, priority 0, no SLO) unless configured explicitly — unknown
        tenant names bill against it rather than erroring, so a
        misconfigured client degrades to best-effort instead of 500s.
    pressure_threshold: pool occupancy (used / usable) at which
        weighted-fair admission replaces FCFS.

    ONE manager is shared by every replica's scheduler in a fleet
    (disagg.py passes it through scheduler_kwargs): in-flight counts
    are per-replica (each scheduler computes its own), but tenant
    identity, weights and the SLO windows are fleet-global.
    """

    def __init__(self, tenants=(), pressure_threshold=0.85):
        self._lock = threading.Lock()
        self.pressure_threshold = float(pressure_threshold)
        self.tenants = {}
        for t in tenants:
            if t.name in self.tenants:
                raise ValueError(f"duplicate tenant {t.name!r}")
            self.tenants[t.name] = t
        self.tenants.setdefault(DEFAULT_TENANT, Tenant(DEFAULT_TENANT))
        # a burn window per tenant that declared a latency tier
        self._slo = {name: SLOEngine(t.slo)
                     for name, t in self.tenants.items()
                     if t.slo is not None}
        self._breached = {name: False for name in self._slo}
        self._requests = {name: 0 for name in self.tenants}

    # ------------------------------------------------------------ lookups
    def tenant(self, name):
        """The cohort for `name` (unknown names fall back to the
        default tenant — best-effort, never an error)."""
        return self.tenants.get(str(name),
                                self.tenants[DEFAULT_TENANT])

    def priority(self, name):
        return self.tenant(name).priority

    def weight(self, name):
        return self.tenant(name).weight

    # -------------------------------------------------- admission fairness
    def under_pressure(self, pool):
        """True when the block pool is scarce enough that admission
        order becomes a fairness decision (no pool = dense engine =
        never)."""
        if pool is None or pool.usable == 0:
            return False
        return pool.used / pool.usable >= self.pressure_threshold

    def pick_admission(self, queued, in_flight_by_tenant):
        """Index into `queued` of the next request to admit under
        pressure: the FIRST queued request of the tenant with the least
        weighted in-flight cost (in_flight / weight) — FCFS within a
        tenant, weighted-fair across tenants. A tenant with nothing in
        flight costs 0, so starvation is impossible: every tenant's
        head request eventually has the cheapest cost."""
        best_i, best_cost = 0, None
        seen = set()
        for i, req in enumerate(queued):
            name = self.tenant(getattr(req, "tenant",
                                       DEFAULT_TENANT)).name
            if name in seen:
                continue             # FCFS within the tenant
            seen.add(name)
            cost = (in_flight_by_tenant.get(name, 0)
                    / self.tenant(name).weight)
            if best_cost is None or cost < best_cost:
                best_i, best_cost = i, cost
        bb = blackbox.get_recorder()
        if bb is not None and len(queued) > 1:
            # journal only non-trivial picks: a 1-deep queue is FCFS
            # whatever the weights say
            req = queued[best_i]
            bb.admission(getattr(req, "request_id", None),
                         verdict="picked", basis="weighted_fair",
                         tenant=getattr(req, "tenant", DEFAULT_TENANT),
                         trace_id=getattr(req, "trace_id", None),
                         queue_index=best_i,
                         cost=None if best_cost is None
                         else round(best_cost, 4))
        return best_i

    # ------------------------------------------------------------- windows
    def observe(self, request):
        """Feed one FINALIZED request into its tenant's window (duck-
        typed on .ttft/.tpot/.finish_reason like SLOEngine). Rejected
        requests count toward the request tally but not the SLO window
        — admission control shedding is not a served request."""
        name = self.tenant(getattr(request, "tenant",
                                   DEFAULT_TENANT)).name
        _TENANT_REQUESTS.labels(tenant=name).inc()
        with self._lock:
            self._requests[name] = self._requests.get(name, 0) + 1
        eng = self._slo.get(name)
        if eng is not None and request.finish_reason != "rejected":
            eng.observe_request(request)

    def evaluate(self, publish=True):
        """Per-tenant burn verdicts: {tenant: evaluate() dict}. With
        publish, the tenant-labeled gauges update and breach
        TRANSITIONS journal (`slo` events tagged with the tenant, the
        runlog's per-tenant rows)."""
        out = {}
        for name, eng in self._slo.items():
            verdict = eng.evaluate(publish=False)
            out[name] = verdict
            if not publish:
                continue
            _TENANT_BURN.labels(tenant=name).set(
                round(verdict["burn_rate"], 4))
            _TENANT_ATTAINMENT.labels(tenant=name).set(
                round(verdict["attainment"], 4))
            breached = bool(verdict["breached"])
            with self._lock:
                transition = breached != self._breached[name]
                self._breached[name] = breached
            if transition:
                rec = flight_recorder.get_recorder()
                if rec is not None:
                    rec.slo(burn_rate=round(verdict["burn_rate"], 4),
                            action=("burn_alert" if breached
                                    else "burn_clear"),
                            attainment=round(verdict["attainment"], 4),
                            slo=verdict["worst"], tenant=name)
        return out

    # ------------------------------------------------------------ reporting
    def summary(self):
        """Per-tenant rollup for bench rows and health payloads:
        config + request tally + the current window verdict (None for
        tenants without an SLO tier)."""
        verdicts = self.evaluate(publish=False)
        with self._lock:
            requests = dict(self._requests)
        out = {}
        for name, t in self.tenants.items():
            v = verdicts.get(name)
            out[name] = {
                "weight": t.weight,
                "priority": t.priority,
                "requests": requests.get(name, 0),
                "attainment": (None if v is None
                               else round(v["attainment"], 4)),
                "burn_rate": (None if v is None
                              else round(v["burn_rate"], 4)),
                "breached": None if v is None else bool(v["breached"]),
            }
        return out


def as_manager(qos):
    """Normalize the `qos=` surface: None / a prebuilt QoSManager pass
    through; an iterable of Tenants builds one."""
    if qos is None or isinstance(qos, QoSManager):
        return qos
    return QoSManager(tenants=qos)
