"""Token-exact request migration.

A `FleetRequest` is the client's handle for the life of one generation
REQUEST, across however many replicas end up serving it. Each hop is an
ordinary replica-local `serving.Request`; when a replica dies (killed
or degraded) the fleet absorbs the tokens that hop already streamed and
resubmits the CONTINUATION — original prompt + every token generated so
far — to a healthy replica. That is exactly the preemption-by-recompute
discipline the paged scheduler already proves token-exact: the
continuation's re-prefill recomputes K/V for the full prefix (mostly
prefix-cache hits when the blocks survived), and its frontier logits
produce the NEXT token of the same greedy trajectory, because every
replica serves digest-verified identical weights (replica.py).

Greedy requests are therefore bitwise-identical to a no-fault run —
the chaos harness's replica_failover scenario asserts it. Sampled
requests (do_sample=True) migrate and complete too, but land on a
different PRNG stream, so their tail is distribution-identical, not
bit-identical; same caveat as preemption.
"""
import itertools
import threading
import time

from ..request import RequestState

#: migrations allowed per request before it resolves "error" — replicas
#: dying faster than this is an outage, not a livelock worth chasing
DEFAULT_MAX_MIGRATIONS = 3


class FleetRequest:
    """One generation request as the fleet sees it.

    `output_tokens` is the stitched stream: tokens absorbed from dead
    replicas followed by the live hop's tokens — the client never sees
    the seam. `on_token` fires with (fleet_request, token) for every
    token whichever replica produced it; exceptions stay contained
    per-request by the underlying engine's callback guard.
    """
    _ids = itertools.count(1)
    #: fleet trace ids live above the replica-local Request id space so
    #: a fleet request's chrome flow can never collide with a direct
    #: (non-fleet) request recorded in the same trace
    _TRACE_BASE = 1 << 20

    def __init__(self, prompt, max_tokens=16, eos_token_id=None,
                 timeout=None, on_token=None, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0,
                 stop_sequences=None, logit_bias=None, token_mask=None,
                 tenant="default", priority=None):
        self.request_id = next(FleetRequest._ids)
        # ONE trace id for the life of the request: every hop's Request
        # inherits it (_submit_kwargs), so the spans a migration leaves
        # on two replicas link into a single chrome flow
        self.trace_id = FleetRequest._TRACE_BASE + self.request_id
        self.prompt = [int(t) for t in prompt]
        self.max_tokens = int(max_tokens)
        self.eos_token_id = eos_token_id
        self.timeout = None if timeout is None else float(timeout)
        self.on_token = on_token
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        # the scenario surface survives migration: the continuation hop
        # must sample under the SAME knobs or the tail of a migrated
        # request is a different request
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.stop_sequences = stop_sequences
        self.logit_bias = logit_bias
        self.token_mask = token_mask
        # QoS identity, carried for the LIFE of the request: every hop's
        # _submit_kwargs forwards both, so a migration or handoff can
        # never silently demote a premium request to the default cohort
        # (the PR 15 sampling-params discipline). priority=None defers
        # to the tenant's configured rank at fleet admission (qos.py).
        self.tenant = str(tenant)
        self.priority = priority
        # a block-level KV payload staged by a prefill-role replica:
        # consumed by the NEXT dispatch (the decode hop's admission
        # imports it instead of re-running prefill), then cleared
        self._handoff_payload = None
        # seed provenance (serving/blackbox.py): the first hop's
        # scheduler stamps its engine's PRNG-chain seed on the hop-local
        # Request; _attach copies it here so the fleet handle names the
        # seed its sampled stream started from
        self.seed = None

        self.submit_time = None      # stamped once, at fleet admission
        self.migrations = 0
        self.replica = None          # current Replica handle
        self.current = None          # current replica-local Request
        self._prior = []             # tokens from hops that died
        self._first_token_abs = None  # banked from a dead hop, so TTFT
                                      # survives the hop that earned it
        self.finish_reason = None
        self.error = None
        self._done = threading.Event()
        # orders _absorb's prior-extend/current-detach pair against a
        # concurrent output_tokens read — without it a streaming client
        # polling mid-migration sees the dead hop's tokens TWICE
        self._tok_lock = threading.Lock()

    # ------------------------------------------------------------- views
    @property
    def output_tokens(self):
        with self._tok_lock:
            cur = ([] if self.current is None
                   else self.current.output_tokens)
            return self._prior + cur

    @property
    def done(self):
        return self._done.is_set()

    @property
    def state(self):
        if self.done:
            return (RequestState.REJECTED
                    if self.finish_reason == "rejected"
                    else RequestState.DONE)
        return (self.current.state if self.current is not None
                else RequestState.QUEUED)

    @property
    def callback_error(self):
        return (None if self.current is None
                else self.current.callback_error)

    def wait(self, timeout=None):
        return self._done.wait(timeout)

    @property
    def latency(self):
        if self.submit_time is None or not self.done:
            return None
        return self._finish_time - self.submit_time

    @property
    def first_token_time(self):
        """When the FIRST token of the stitched stream landed — the
        first hop's timestamp even after that hop's replica died."""
        if self._first_token_abs is not None:
            return self._first_token_abs
        cur = self.current
        return None if cur is None else cur.first_token_time

    @property
    def ttft(self):
        """Fleet-level time-to-first-token (the client's view: from
        fleet admission, whatever replica ended up serving it)."""
        first = self.first_token_time
        if first is None or self.submit_time is None:
            return None
        return first - self.submit_time

    @property
    def tpot(self):
        """Mean inter-token latency of the stitched stream: first token
        to completion over the gap count — migration stalls INCLUDE
        themselves, because the client experienced them."""
        first = self.first_token_time
        if first is None or not self.done:
            return None
        n = len(self.output_tokens)
        if n < 2:
            return None
        return (self._finish_time - first) / (n - 1)

    # -------------------------------------------------- router internals
    def _mark_submitted(self):
        if self.submit_time is None:
            self.submit_time = time.monotonic()

    def _submit_kwargs(self):
        """kwargs for the next hop's Scheduler.submit(): the
        continuation prompt, the REMAINING token budget and wall-clock
        budget, and the callback shimmed to this fleet handle."""
        remaining_t = None
        if self.timeout is not None:
            elapsed = time.monotonic() - (self.submit_time or
                                          time.monotonic())
            remaining_t = max(1e-3, self.timeout - elapsed)
        kw = {
            "prompt": self.prompt + self._prior,
            "max_tokens": self.max_tokens - len(self._prior),
            "eos_token_id": self.eos_token_id,
            "timeout": remaining_t,
            "do_sample": self.do_sample,
            "temperature": self.temperature,
            "top_k": self.top_k,
            "top_p": self.top_p,
            "stop_sequences": self.stop_sequences,
            "logit_bias": self.logit_bias,
            # stop matching must see ACROSS the migration seam: the
            # dead hop's tokens become prompt on the next hop, so the
            # tail of the prior output stream rides along as context —
            # a stop sequence whose first half was already streamed
            # still fires on its second half
            "stop_context": self._stop_tail(),
            # trace continuity across migration: the resumed hop's
            # spans carry the SAME fleet trace id, so the halves of a
            # migrated request link instead of starting a fresh trace
            "trace_id": self.trace_id,
            # QoS identity rides EVERY hop (tenant attainment and
            # priority preemption would silently break across a
            # migration or handoff otherwise)
            "tenant": self.tenant,
            "priority": 0 if self.priority is None else int(self.priority),
        }
        if self._handoff_payload is not None:
            kw["handoff"] = self._handoff_payload
        if self.on_token is not None:
            fleet_req = self

            def shim(_req, token):
                fleet_req.on_token(fleet_req, token)
            kw["on_token"] = shim
        if self.token_mask is not None:
            fleet_req = self

            def mask_shim(_req):
                # the mask sees the FLEET view: its stitched output
                # stream, not the hop-local request whose prior tokens
                # migrated into the prompt
                return fleet_req.token_mask(fleet_req)
            kw["token_mask"] = mask_shim
        return kw

    def _stop_tail(self):
        """The prior output stream's tail a continuation hop needs for
        seam-spanning stop matching: the longest stop sequence minus
        one tokens (None when no multi-token stop sequence exists)."""
        longest = max((len(s) for s in (self.stop_sequences or [])),
                      default=0)
        if longest < 2 or not self._prior:
            return None
        return self._prior[-(longest - 1):]

    def _absorb(self):
        """A hop died: bank its clean tokens (every emitted token
        precedes the fault — the non-finite sentinel freezes a lane
        BEFORE a bad token reaches the host, and a kill harvests only
        what was streamed) and detach from the dead Request."""
        with self._tok_lock:
            if self.current is not None:
                self._prior.extend(self.current.output_tokens)
                if self._first_token_abs is None:
                    self._first_token_abs = self.current.first_token_time
            self.current = None
            self.replica = None

    def _attach(self, replica, request):
        self.replica = replica
        self.current = request
        if self.seed is None:       # first hop wins: later hops replay
            self.seed = getattr(request, "seed", None)

    def _finalize(self, reason, error=None):
        self.finish_reason = reason
        if error is not None:
            self.error = str(error)
        self._finish_time = time.monotonic()
        self._done.set()

    def _finalize_from(self, request):
        """Propagate a completed hop's resolution to the fleet handle
        (the normal, no-fault path)."""
        self._finalize(request.finish_reason, error=request.error)

    def __repr__(self):
        return (f"FleetRequest(id={self.request_id}, state={self.state}, "
                f"tenant={self.tenant!r}, seed={self.seed}, "
                f"generated={len(self.output_tokens)}/{self.max_tokens}, "
                f"migrations={self.migrations}, "
                f"finish={self.finish_reason})")
