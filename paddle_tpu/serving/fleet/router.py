"""FleetRouter: prefix-affinity routing, failover, and elastic scale
over N serving-engine replicas.

One engine is a single point of failure; the fleet turns `serving/`
into a service. The router owns a rotation of `Replica`s (replica.py)
and three behaviors:

  * **Routing.** Every admission is scored against each replica's
    prefix cache via the block pool's chain hashes (one
    `BlockPool.prompt_hashes` walk scored with `peek_prefix_hashes`
    per replica — the sha256 chain the paged engine already computes
    over full prompt blocks IS the affinity key): a
    shared-system-prompt cohort lands on the replica that already
    holds its K/V blocks, so the fleet-wide prefix-hit rate approaches
    the single-engine rate instead of dividing by N. No replica holds
    the prefix → least-loaded; `policy="round_robin"` is the A/B
    baseline the bench compares against.
  * **Failover.** The router watches each replica's real health (the
    same ok/degraded/draining states /healthz reports, plus queue
    depth and `cache_blocks_used`) and treats a dead or degraded
    replica as a REPLACEMENT event: its accepted requests are
    evacuated with the tokens they already streamed and resubmitted
    token-exactly elsewhere (migration.py), and a digest-verified
    replacement is spawned into the rotation. Chaos points
    `fleet.replica_kill` / `fleet.router_dispatch` make both paths
    provable on demand (scripts/chaos_serving.py replica_failover).
  * **Elastic scale.** Offered load is read off live telemetry (queue
    depth per routable replica): sustained pressure spawns a replica
    (warm start — the factory's weights must match the fleet's
    reference digest), sustained idleness drains the newest one and
    retires it once its accepted work finishes. Accepted work is never
    dropped by scaling in either direction.

Thread-model: `submit()` is safe from producer threads; `step()` —
one round across every replica — runs wherever `run()` is driven, same
as the single-engine Scheduler.
"""
import threading

from ...utils import chaos, flight_recorder
from .metrics import FleetMetrics
from .migration import DEFAULT_MAX_MIGRATIONS, FleetRequest
from .replica import ReplicaSupervisor

POLICIES = ("affinity", "least_loaded", "round_robin")


class FleetRouter:
    """Router + supervisor loop over N replicas.

    engine_factory: zero-arg callable building one serving engine
        (replicas may share one model instance — each engine owns its
        caches/pool; the supervisor digest-checks the weights).
    replicas: initial rotation size (also the replacement target).
    policy: "affinity" (default) | "least_loaded" | "round_robin".
    migrate: False disables failover migration — a killed replica's
        in-flight requests then resolve "error" (the chaos harness's
        no-migration positive control).
    min_replicas/max_replicas + scale_up_queue_depth: elastic range;
        scale_up_queue_depth=None disables autoscaling.
    """

    def __init__(self, engine_factory, replicas=2, policy="affinity",
                 scheduler_kwargs=None, migrate=True,
                 max_migrations=DEFAULT_MAX_MIGRATIONS,
                 min_replicas=None, max_replicas=None,
                 scale_up_queue_depth=None, scale_down_idle_rounds=8,
                 auto_replace=True, verify_state=True):
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, "
                             f"got {policy!r}")
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.policy = policy
        self.migrate = bool(migrate)
        self.max_migrations = int(max_migrations)
        self.auto_replace = bool(auto_replace)
        self.min_replicas = int(min_replicas or 1)
        self.max_replicas = int(max_replicas or max(replicas, 1))
        self.scale_up_queue_depth = scale_up_queue_depth
        self.scale_down_idle_rounds = int(scale_down_idle_rounds)
        self.supervisor = ReplicaSupervisor(
            engine_factory, scheduler_kwargs=scheduler_kwargs,
            verify_state=verify_state)
        self.metrics = FleetMetrics()
        self._lock = threading.Lock()        # rotation + live-request set
        # one fleet round at a time; REENTRANT so kill_replica — which
        # step() itself drives on the REPLICA_KILL chaos point — can
        # also serialize an operator/watch-loop thread's kill against
        # the round in progress (finalization reads fr.current twice)
        self._step_lock = threading.RLock()
        self.replicas = [self.supervisor.spawn() for _ in range(replicas)]
        self._live = []                      # unresolved FleetRequests
        self._retired_metric_snaps = []      # final snapshots of the dead
        self._dead_total = 0                 # replicas killed/degraded
        self._target = int(replicas)         # replacement/scale target
        self._rr = 0
        self._idle_rounds = 0

    # ---------------------------------------------------------- admission
    def submit(self, request=None, **kw):
        """Route one request (kwargs as serving.Request: prompt,
        max_tokens, eos_token_id, timeout, on_token, do_sample,
        temperature). Returns a FleetRequest; raises ValueError when no
        replica accepts it (every replica shedding is the fleet-level
        admission-control signal)."""
        if request is None:
            request = FleetRequest(**kw)
        request._mark_submitted()
        # live BEFORE dispatch: _retire_replica scans _live for a dead
        # replica's work, and a request attached concurrently with the
        # retirement must be visible to that scan or it is never
        # migrated (producer threads submit while step() retires)
        with self._lock:
            self._live.append(request)
        try:
            self._dispatch(request)          # raises on total refusal
        except ValueError:
            with self._lock:
                if request in self._live:
                    self._live.remove(request)
            raise
        return request

    def _route(self, prompt):
        """Candidate replicas in preference order + the policy label
        that placed the head choice. Affinity scores count the leading
        full prompt blocks each replica's pool could serve from cache;
        ties (and score 0) fall back to least-loaded."""
        with self._lock:
            live = [r for r in self.replicas if r.routable]
            if self.policy == "round_robin" and live:
                start = self._rr % len(live)   # read-modify-write under
                self._rr += 1                  # the lock: submit() is
        if not live:                           # producer-thread safe
            raise RuntimeError("fleet has no routable replicas")
        if self.policy == "round_robin":
            return live[start:] + live[:start], "round_robin"
        order = sorted(live, key=lambda r: (r.load(), r.replica_id))
        policy = "least_loaded"
        if self.policy == "affinity":
            # hash the prompt ONCE: the chain hashes are content-only,
            # so one prompt_hashes() walk scores every replica's pool
            # by lookups instead of N sha256 chains per admission
            pool = next((p for p in (getattr(r.engine, "block_pool",
                                             None) for r in live)
                         if p is not None), None)
            if pool is not None:
                hashes = pool.prompt_hashes(prompt)
                score = {r.replica_id: r.affinity_hashes(hashes)
                         for r in live}
                if max(score.values()) > 0:
                    order = sorted(live, key=lambda r: (
                        -score[r.replica_id], r.load(), r.replica_id))
                    policy = "affinity"
        return order, policy

    def _dispatch(self, fr, continuation=False):
        """Hand `fr` to the best replica, walking the candidate order
        on failure: a dispatch fault (the ROUTER_DISPATCH chaos point
        stands in for a crashed/unreachable replica) or a replica-side
        shed moves to the next candidate — an accepted request is never
        lost to one bad hand-off. Total refusal resolves the request
        ("rejected" fresh, "error" for a migrating continuation) and
        raises ValueError for fresh submits."""
        kw = fr._submit_kwargs()
        try:
            candidates, policy = self._route(kw["prompt"])
        except RuntimeError as e:
            fr._finalize("error" if continuation else "rejected", error=e)
            if not continuation:
                self.metrics.on_rejected()
                raise ValueError(str(e))
            return
        last_err = None
        for i, replica in enumerate(candidates):
            if i:
                self.metrics.on_dispatch_retry()
            try:
                if chaos.enabled():
                    chaos.fire(chaos.ROUTER_DISPATCH,
                               replica=replica.replica_id,
                               request_id=fr.request_id)
                req = replica.scheduler.submit(**kw)
            except Exception as e:   # noqa: BLE001 — dispatch fault
                last_err = e         # barrier: next candidate takes it
                continue
            with self._lock:
                fr._attach(replica, req)
                # the replica may have been retired between _route and
                # submit — its kill() already harvested the scheduler,
                # and _retire_replica's owned scan may have run before
                # the attach, so this hop is ours to fail over
                lost = replica not in self.replicas
            self.metrics.on_routed(policy)
            if lost:
                self._migrate(fr, reason="retired mid-dispatch",
                              src=replica)
            return
        why = f"no replica accepted the request ({last_err!r})"
        fr._finalize("error" if continuation else "rejected", error=why)
        if not continuation:
            self.metrics.on_rejected()
            raise ValueError(why)

    # ---------------------------------------------------------- the loop
    def step(self):
        """One fleet round: honor any injected replica kill, drive one
        scheduling round on every live replica, replace the dead and
        degraded (migrating their work), finalize completions, and
        autoscale. Returns the number of unresolved fleet requests."""
        with self._step_lock:
            if chaos.enabled():
                hit = chaos.value(chaos.REPLICA_KILL)
                if hit is not None:
                    with self._lock:
                        live = [r for r in self.replicas
                                if r.state != "dead"]
                    if live:
                        self.kill_replica(live[int(hit) % len(live)])
            for replica in self._rotation():
                if replica.state == "dead":
                    continue
                replica.scheduler.step()
                if replica.scheduler.degraded:
                    self._retire_replica(replica, reason="degraded")
            self._finalize_completed()
            self._autoscale()
            with self._lock:
                self.metrics.publish_states(self.replicas,
                                            dead_total=self._dead_total)
        return self.outstanding()

    def run(self, max_rounds=None):
        """Drive step() until every accepted request resolves (or
        max_rounds). Producer threads may keep submit()ing."""
        rounds = 0
        while self.step():
            rounds += 1
            if max_rounds is not None and rounds >= max_rounds:
                break
        return rounds

    def generate(self, prompt, **kw):
        """Blocking single-request convenience (mirrors
        Scheduler.generate)."""
        fr = self.submit(prompt=prompt, **kw)
        while not fr.done:
            self.step()
        return fr.output_tokens

    def _rotation(self):
        with self._lock:
            return list(self.replicas)

    def outstanding(self):
        with self._lock:
            return len(self._live)

    # ----------------------------------------------------------- failover
    def kill_replica(self, replica, reason="killed"):
        """Kill one replica (chaos, an operator, or the watch loop) and
        fail its work over: replacement spawned first so migration has
        a routable target even in a one-replica fleet. Safe from any
        thread — serializes with the fleet round in progress."""
        with self._step_lock:
            if self._retire_replica(replica, reason=reason):
                self.metrics.on_kill()   # count only kills that retired
                                         # something (stale handles no-op)

    def _retire_replica(self, replica, reason):
        """Returns True when `replica` was actually retired here (False:
        already gone — a second kill on a stale handle is a no-op)."""
        with self._lock:
            if replica not in self.replicas:
                return False
            self.replicas.remove(replica)
            self._dead_total += 1
        replica.kill()
        with self._lock:
            # its completed work must stay in fleet-wide rollups
            # (bench rows would silently undercount otherwise)
            self._retired_metric_snaps.append(
                replica.scheduler.metrics.snapshot())
        rec = flight_recorder.get_recorder()
        if rec is not None:
            rec.fault(kind="replica_" + reason, action="replace",
                      error=f"replica {replica.replica_id}")
        if self.auto_replace:
            with self._lock:
                short = sum(1 for r in self.replicas
                            if r.routable) < self._target
            if short:
                try:
                    self._spawn(restart=True)
                except Exception as e:  # noqa: BLE001 — failover must
                    # still migrate the dead replica's work even when
                    # the replacement cannot be built (digest mismatch,
                    # allocation failure): survivors take it, or total
                    # refusal resolves it 'error' — never stranded
                    if rec is not None:
                        rec.fault(kind="replica_spawn_failed",
                                  action="continue",
                                  error=f"{type(e).__name__}: {e}")
        with self._lock:
            owned = [fr for fr in self._live if fr.replica is replica]
        for fr in owned:
            cur = fr.current
            if cur is not None and cur.done and \
                    cur.finish_reason not in ("error", "rejected"):
                self._finalize_one(fr)   # finished before the fault
            else:
                self._migrate(fr, reason=reason, src=replica)
        return True

    def _migrate(self, fr, reason, src=None):
        """Resubmit one evacuated request's continuation (prompt +
        tokens generated so far) to a healthy replica — token-exact for
        greedy requests (migration.py). Budget-bounded; a continuation
        at the cache horizon finishes "length" exactly as it would have
        on the original replica. `src` makes the call idempotent per
        hop: the retire scan and a racing dispatch may both see the
        same dead hop, and whoever detaches it first wins."""
        with self._lock:
            if src is not None and fr.replica is not src:
                return               # this hop was already failed over
            src_id = (None if fr.replica is None
                      else fr.replica.replica_id)
            cur = fr.current
            fr._absorb()             # detach atomically with the check
        if cur is not None and not cur.done:
            cur._fail(f"replica {src_id} {reason}")
        if not self.migrate:
            self._finalize_one(fr, forced=(
                "error", f"replica {src_id} {reason}; migration disabled"))
            return
        fr.migrations += 1
        if fr.migrations > self.max_migrations:
            self._finalize_one(fr, forced=(
                "error", f"migration budget spent ({self.max_migrations}x)"))
            return
        if len(fr._prior) >= fr.max_tokens:
            self._finalize_one(fr, forced=("max_tokens", None))
            return
        if self._continuation_refused(fr.prompt + fr._prior) is not None:
            # the continuation cannot be re-admitted ANYWHERE in this
            # fleet — the cache horizon, or on a dense fleet the prefill
            # bucket (re-prefill cannot exceed it even though the dead
            # replica was already past prefill): deliver the tokens
            # generated so far, terminated "length", not "error"
            self._finalize_one(fr, forced=("length", None))
            return
        self._dispatch(fr, continuation=True)
        if fr.replica is not None:
            self.metrics.on_migration(request_id=fr.request_id,
                                      src=src_id,
                                      dst=fr.replica.replica_id)
        else:                        # total refusal: _dispatch resolved it
            with self._lock:
                if fr in self._live:
                    self._live.remove(fr)

    def _continuation_refused(self, cont_prompt):
        """Engine-level admissibility of a migrated continuation — the
        ENGINE owns its admission rules (dense prefill bucket, paged
        horizon/pool capacity), so ask one live engine rather than
        re-deriving them here; the fleet is homogeneous (one factory).
        None = admissible (or nothing alive to ask — dispatch resolves
        that case)."""
        with self._lock:
            for r in self.replicas:
                if r.state != "dead":
                    return r.engine.validate_prompt(cont_prompt)
        return None

    # -------------------------------------------------------- completions
    def _finalize_one(self, fr, forced=None):
        if forced is not None:
            fr._finalize(forced[0], error=forced[1])
        else:
            fr._finalize_from(fr.current)
        with self._lock:
            if fr in self._live:
                self._live.remove(fr)

    def _finalize_completed(self):
        with self._lock:
            done = [fr for fr in self._live
                    if fr.current is not None and fr.current.done]
        for fr in done:
            self._finalize_one(fr)

    # ----------------------------------------------------------- scaling
    def _spawn(self, restart=False):
        replica = self.supervisor.spawn()
        with self._lock:
            self.replicas.append(replica)
        if restart:
            self.metrics.on_restart()
        return replica

    def _autoscale(self):
        """Elastic scale on live telemetry. Scale-up: sustained queue
        pressure per routable replica. Scale-down: a fully idle fleet
        for `scale_down_idle_rounds` consecutive rounds drains the
        newest replica (accepted work still completes) and retires it
        once empty. Replicas draining for scale-down leave the rotation
        here; replicas draining by operator drain() do too."""
        with self._lock:
            drained = [r for r in self.replicas
                       if r.state == "draining" and r.drained()]
            for r in drained:
                self.replicas.remove(r)
                self._retired_metric_snaps.append(
                    r.scheduler.metrics.snapshot())
        for r in drained:
            r.engine.stop_metrics_server()
        if self.scale_up_queue_depth is None:
            return
        with self._lock:
            live = [r for r in self.replicas if r.routable]
        if not live:
            return
        queued = sum(r.scheduler.queue_depth() for r in live)
        busy = sum(r.load() for r in live)
        if queued / len(live) > self.scale_up_queue_depth \
                and len(live) < self.max_replicas:
            self._target = len(live) + 1
            self._spawn()
            self.metrics.on_scale("up")
            self._idle_rounds = 0
        elif busy == 0 and len(live) > self.min_replicas:
            self._idle_rounds += 1
            if self._idle_rounds >= self.scale_down_idle_rounds:
                victim = max(live, key=lambda r: r.replica_id)
                victim.drain()
                self._target = len(live) - 1
                self.metrics.on_scale("down")
                self._idle_rounds = 0
        else:
            self._idle_rounds = 0

    # ------------------------------------------------------------- admin
    def health(self):
        """Fleet-level health view: per-replica /healthz payloads plus
        the rotation summary (what an external dashboard polls)."""
        with self._lock:
            reps = list(self.replicas)
        return {
            "replicas": [r.health() for r in reps],
            "routable": sum(1 for r in reps if r.routable),
            "target_replicas": self._target,
            "policy": self.policy,
        }

    def drain(self):
        """Stop admitting fleet-wide; accepted work runs to completion
        (drive run() until it returns 0)."""
        for r in self._rotation():
            if r.state in ("ok", "draining"):
                r.drain()

    def shutdown(self, max_rounds=None):
        """drain() + drive to empty + stop every replica's exporter."""
        self.drain()
        rounds = self.run(max_rounds=max_rounds)
        for r in self._rotation():
            r.engine.stop_metrics_server()
        return rounds

    def reset_metrics(self):
        """Fresh fleet + per-replica tallies (the bench builds one
        fleet and measures each load point separately). Only valid on
        an idle fleet — a new Scheduler per replica would strand
        in-flight work."""
        if self.outstanding():
            raise RuntimeError("reset_metrics on a non-idle fleet")
        self.metrics = FleetMetrics()
        with self._lock:
            self._retired_metric_snaps = []
        for r in self._rotation():
            r.renew_scheduler()

    def retired_metric_snapshots(self):
        """Final ServingMetrics snapshots of replicas retired (killed,
        degraded-replaced, or drained away) since the last
        reset_metrics() — a fleet-wide rollup must include the work
        they completed before leaving the rotation."""
        with self._lock:
            return list(self._retired_metric_snaps)
