"""FleetRouter: prefix-affinity routing, failover, and elastic scale
over N serving-engine replicas.

One engine is a single point of failure; the fleet turns `serving/`
into a service. The router owns a rotation of `Replica`s (replica.py)
and three behaviors:

  * **Routing.** Every admission is scored against each replica's
    prefix cache via the block pool's chain hashes (one
    `BlockPool.prompt_hashes` walk scored with `peek_prefix_hashes`
    per replica — the sha256 chain the paged engine already computes
    over full prompt blocks IS the affinity key): a
    shared-system-prompt cohort lands on the replica that already
    holds its K/V blocks, so the fleet-wide prefix-hit rate approaches
    the single-engine rate instead of dividing by N. No replica holds
    the prefix → least-loaded; `policy="round_robin"` is the A/B
    baseline the bench compares against.
  * **Failover.** The router watches each replica's real health (the
    same ok/degraded/draining states /healthz reports, plus queue
    depth and `cache_blocks_used`) and treats a dead or degraded
    replica as a REPLACEMENT event: its accepted requests are
    evacuated with the tokens they already streamed and resubmitted
    token-exactly elsewhere (migration.py), and a digest-verified
    replacement is spawned into the rotation. Chaos points
    `fleet.replica_kill` / `fleet.router_dispatch` make both paths
    provable on demand (scripts/chaos_serving.py replica_failover).
  * **Elastic scale.** Offered load is read off live telemetry (queue
    depth per routable replica): sustained pressure spawns a replica
    (warm start — the factory's weights must match the fleet's
    reference digest), sustained idleness drains the newest one and
    retires it once its accepted work finishes. Accepted work is never
    dropped by scaling in either direction.

Thread-model: `submit()` is safe from producer threads; `step()` —
one round across every replica — runs wherever `run()` is driven, same
as the single-engine Scheduler.
"""
import threading

from ...utils import (chaos, flight_recorder, profiler, telemetry,
                      timeseries)
from .. import blackbox
from ..slo import as_engine as _slo_as_engine
from .metrics import FleetMetrics, FleetRegistry
from .migration import DEFAULT_MAX_MIGRATIONS, FleetRequest
from .replica import ReplicaSupervisor

POLICIES = ("affinity", "least_loaded", "round_robin")


class FleetRouter:
    """Router + supervisor loop over N replicas.

    engine_factory: zero-arg callable building one serving engine
        (replicas may share one model instance — each engine owns its
        caches/pool; the supervisor digest-checks the weights).
    replicas: initial rotation size (also the replacement target).
    policy: "affinity" (default) | "least_loaded" | "round_robin".
    migrate: False disables failover migration — a killed replica's
        in-flight requests then resolve "error" (the chaos harness's
        no-migration positive control).
    min_replicas/max_replicas + scale_up_queue_depth: elastic range;
        scale_up_queue_depth=None disables autoscaling.
    slo: an SLOPolicy (or prebuilt SLOEngine, serving/slo.py). When
        set, every finalized request feeds the sliding window and the
        autoscaler consumes error-budget BURN RATE instead of queue
        depth: scale up on fast burn (the latency promise is being
        broken), drain the newest replica on sustained surplus. Burn
        transitions journal through the flight recorder and the
        verdict rides `health()` / the fleet exporter's /healthz.
    """

    def __init__(self, engine_factory, replicas=2, policy="affinity",
                 scheduler_kwargs=None, migrate=True,
                 max_migrations=DEFAULT_MAX_MIGRATIONS,
                 min_replicas=None, max_replicas=None,
                 scale_up_queue_depth=None, scale_down_idle_rounds=8,
                 auto_replace=True, verify_state=True, slo=None,
                 roles=None):
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, "
                             f"got {policy!r}")
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if roles is not None and len(roles) != replicas:
            raise ValueError(f"roles ({len(roles)}) must name every "
                             f"initial replica ({replicas})")
        self.policy = policy
        self.migrate = bool(migrate)
        self.max_migrations = int(max_migrations)
        self.auto_replace = bool(auto_replace)
        # with an SLO configured, burn-surplus scale-DOWN is active —
        # the configured size is then the default floor, so opting into
        # SLO observability alone cannot silently shrink a fixed-size
        # fleet; pass min_replicas explicitly to allow draining below it
        if min_replicas is None:
            min_replicas = replicas if slo is not None else 1
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas or max(replicas, 1))
        self.scale_up_queue_depth = scale_up_queue_depth
        self.scale_down_idle_rounds = int(scale_down_idle_rounds)
        self.supervisor = ReplicaSupervisor(
            engine_factory, scheduler_kwargs=scheduler_kwargs,
            verify_state=verify_state)
        self.metrics = FleetMetrics()
        self._lock = threading.Lock()        # rotation + live-request set
        # one fleet round at a time; REENTRANT so kill_replica — which
        # step() itself drives on the REPLICA_KILL chaos point — can
        # also serialize an operator/watch-loop thread's kill against
        # the round in progress (finalization reads fr.current twice)
        self._step_lock = threading.RLock()
        roles = roles or ["unified"] * replicas
        self.replicas = [self.supervisor.spawn(role=role)
                         for role in roles]
        self._live = []                      # unresolved FleetRequests
        self._retired_metric_snaps = []      # final snapshots of the dead
        self._dead_total = 0                 # replicas killed/degraded
        self._target = int(replicas)         # replacement/scale target
        self._rr = 0
        self._idle_rounds = 0
        # fleet-round counter stamping every journaled routing decision
        # (serving/blackbox.py): replay re-forces recorded kills at the
        # same round boundary, so the counter ticks at the TOP of
        # step(), before the chaos kill check
        self._round = 0
        # SLO-driven autoscale state (serving/slo.py)
        self.slo_engine = _slo_as_engine(slo)
        self._scale_cooldown = 0             # rounds until next burn
        self._surplus_rounds = 0             # consecutive low-burn rounds
        self._metrics_server = None
        # observability plane (attach_timeseries): sampled + evaluated
        # once per fleet round, with per-replica queue depths as extra
        # series / detector context (only the router sees all replicas)
        self._sampler = None
        self._alerts = None

    def attach_timeseries(self, sampler=None, alerts=None):
        """Attach the metrics-history sampler and/or an AlertManager to
        the fleet round: each step() samples every registered metric
        plus per-replica queue-depth series, and feeds the depths to the
        queue-skew detector (anomaly.default_fleet_rules).  A retired
        replica's series simply stops — its ladder freezes without
        touching any other series.  Alert state rides health()."""
        if sampler is not None:
            self._sampler = sampler
        if alerts is not None:
            self._alerts = alerts
        return self

    # ---------------------------------------------------------- admission
    def submit(self, request=None, **kw):
        """Route one request (kwargs as serving.Request: prompt,
        max_tokens, eos_token_id, timeout, on_token, do_sample,
        temperature). Returns a FleetRequest; raises ValueError when no
        replica accepts it (every replica shedding is the fleet-level
        admission-control signal)."""
        if request is None:
            request = FleetRequest(**kw)
        request._mark_submitted()
        bb = blackbox.get_recorder()
        if bb is not None:
            # the fleet-origin submit is what window replay re-submits
            # (hop-local scheduler submits carry origin="scheduler" and
            # correlate through the shared trace_id)
            bb.submit(request, origin="fleet", round=self._round)
        # live BEFORE dispatch: _retire_replica scans _live for a dead
        # replica's work, and a request attached concurrently with the
        # retirement must be visible to that scan or it is never
        # migrated (producer threads submit while step() retires)
        with self._lock:
            self._live.append(request)
        try:
            self._dispatch(request)          # raises on total refusal
        except ValueError:
            with self._lock:
                if request in self._live:
                    self._live.remove(request)
            raise
        return request

    def _route(self, prompt, needs_prefill=True):
        """Candidate replicas in preference order + the policy label
        that placed the head choice. Role-specialized replicas
        (disagg.py) filter first: fresh work routes only to prefill-
        capable replicas, handoff continuations only to decode-capable
        ones. Affinity scores count the leading full prompt blocks each
        replica's pool could serve from cache; ties (and score 0) fall
        back to least-loaded."""
        with self._lock:
            live = [r for r in self.replicas
                    if r.routable and r.accepts(needs_prefill)]
            if self.policy == "round_robin" and live:
                start = self._rr % len(live)   # read-modify-write under
                self._rr += 1                  # the lock: submit() is
        if not live:                           # producer-thread safe
            raise RuntimeError("fleet has no routable replicas")
        if self.policy == "round_robin":
            return live[start:] + live[:start], "round_robin"
        order = sorted(live, key=lambda r: (r.load(), r.replica_id))
        policy = "least_loaded"
        if self.policy == "affinity":
            # hash the prompt ONCE: the chain hashes are content-only,
            # so one prompt_hashes() walk scores every replica's pool
            # by lookups instead of N sha256 chains per admission
            pool = next((p for p in (getattr(r.engine, "block_pool",
                                             None) for r in live)
                         if p is not None), None)
            if pool is not None:
                hashes = pool.prompt_hashes(prompt)
                score = {r.replica_id: r.affinity_hashes(hashes)
                         for r in live}
                if max(score.values()) > 0:
                    order = sorted(live, key=lambda r: (
                        -score[r.replica_id], r.load(), r.replica_id))
                    policy = "affinity"
        return order, policy

    def _dispatch(self, fr, continuation=False):
        """Hand `fr` to the best replica, walking the candidate order
        on failure: a dispatch fault (the ROUTER_DISPATCH chaos point
        stands in for a crashed/unreachable replica) or a replica-side
        shed moves to the next candidate — an accepted request is never
        lost to one bad hand-off. Total refusal resolves the request
        ("rejected" fresh, "error" for a migrating continuation) and
        raises ValueError for fresh submits."""
        kw = fr._submit_kwargs()
        try:
            candidates, policy = self._route(
                kw["prompt"], needs_prefill=kw.get("handoff") is None)
        except RuntimeError as e:
            fr._finalize("error" if continuation else "rejected", error=e)
            self._observe_slo(fr)
            if not continuation:
                self.metrics.on_rejected()
                raise ValueError(str(e))
            return
        last_err = None
        for i, replica in enumerate(candidates):
            if i:
                self.metrics.on_dispatch_retry()
            try:
                if chaos.enabled():
                    chaos.fire(chaos.ROUTER_DISPATCH,
                               replica=replica.replica_id,
                               request_id=fr.request_id)
                req = replica.scheduler.submit(**kw)
            except Exception as e:   # noqa: BLE001 — dispatch fault
                last_err = e         # barrier: next candidate takes it
                continue
            with self._lock:
                fr._attach(replica, req)
                # the replica may have been retired between _route and
                # submit — its kill() already harvested the scheduler,
                # and _retire_replica's owned scan may have run before
                # the attach, so this hop is ours to fail over
                lost = replica not in self.replicas
            # the router's leg of the request's chrome flow: QUEUED(s)
            # on the replica row, then this DISPATCH step naming which
            # replica the policy picked (pid 0 = the router's row)
            telemetry.trace_flow_step(
                fr.trace_id, "DISPATCH", replica=replica.replica_id,
                policy=policy, continuation=bool(continuation))
            bb = blackbox.get_recorder()
            if bb is not None:
                bb.hop(kind="dispatch", request_id=fr.request_id,
                       trace_id=fr.trace_id,
                       local_request_id=req.request_id,
                       dst=replica.replica_id, policy=policy,
                       continuation=bool(continuation),
                       round=self._round)
            self.metrics.on_routed(policy)
            if lost:
                self._migrate(fr, reason="retired mid-dispatch",
                              src=replica)
            return
        why = f"no replica accepted the request ({last_err!r})"
        fr._finalize("error" if continuation else "rejected", error=why)
        self._observe_slo(fr)
        if not continuation:
            self.metrics.on_rejected()
            raise ValueError(why)

    # ---------------------------------------------------------- the loop
    def step(self):
        """One fleet round: honor any injected replica kill, drive one
        scheduling round on every live replica, replace the dead and
        degraded (migrating their work), finalize completions, and
        autoscale. Returns the number of unresolved fleet requests."""
        with self._step_lock:
            self._round += 1
            if chaos.enabled():
                hit = chaos.value(chaos.REPLICA_KILL)
                if hit is not None:
                    with self._lock:
                        live = [r for r in self.replicas
                                if r.state != "dead"]
                    if live:
                        self.kill_replica(live[int(hit) % len(live)])
            for replica in self._rotation():
                if replica.state == "dead":
                    continue
                replica.scheduler.step()
                if replica.scheduler.degraded:
                    self._retire_replica(replica, reason="degraded")
            self._finalize_completed()
            self._autoscale()
            with self._lock:
                self.metrics.publish_states(self.replicas,
                                            dead_total=self._dead_total)
                reps = list(self.replicas)
            if self._sampler is not None or self._alerts is not None:
                # one observability pass per fleet round: per-replica
                # queue depths ride along as extra history series (a
                # retired replica drops out — its ladder freezes) and
                # as the queue-skew detector's context
                depths = {str(r.replica_id):
                          float(r.scheduler.queue_depth())
                          for r in reps if r.state != "dead"}
                if self._sampler is not None:
                    self._sampler.maybe_sample(extra={
                        timeseries.series_key("fleet_replica_queue_depth",
                                              {"replica": rid}): d
                        for rid, d in depths.items()})
                if self._alerts is not None:
                    self._alerts.evaluate(
                        {"replica_queue_depths": depths})
        return self.outstanding()

    def run(self, max_rounds=None):
        """Drive step() until every accepted request resolves (or
        max_rounds). Producer threads may keep submit()ing."""
        rounds = 0
        while self.step():
            rounds += 1
            if max_rounds is not None and rounds >= max_rounds:
                break
        return rounds

    def generate(self, prompt, **kw):
        """Blocking single-request convenience (mirrors
        Scheduler.generate)."""
        fr = self.submit(prompt=prompt, **kw)
        while not fr.done:
            self.step()
        return fr.output_tokens

    def _rotation(self):
        with self._lock:
            return list(self.replicas)

    def outstanding(self):
        with self._lock:
            return len(self._live)

    # ----------------------------------------------------------- failover
    def kill_replica(self, replica, reason="killed"):
        """Kill one replica (chaos, an operator, or the watch loop) and
        fail its work over: replacement spawned first so migration has
        a routable target even in a one-replica fleet. Safe from any
        thread — serializes with the fleet round in progress."""
        with self._step_lock:
            if self._retire_replica(replica, reason=reason):
                self.metrics.on_kill()   # count only kills that retired
                                         # something (stale handles no-op)

    def _retire_replica(self, replica, reason):
        """Returns True when `replica` was actually retired here (False:
        already gone — a second kill on a stale handle is a no-op)."""
        with self._lock:
            if replica not in self.replicas:
                return False
            self.replicas.remove(replica)
            self._dead_total += 1
            # its completed work must stay in fleet-wide rollups (bench
            # rows would silently undercount otherwise) — snapshotted
            # in the SAME lock acquisition as the removal, so a
            # concurrent exporter scrape sees the replica in exactly
            # one of {rotation, retired}: counters summed over both
            # stay monotonic (kill() below only evacuates, it cannot
            # change completed-work tallies)
            self._retired_metric_snaps.append(
                replica.scheduler.metrics.snapshot())
        replica.kill()
        rec = flight_recorder.get_recorder()
        if rec is not None:
            rec.fault(kind="replica_" + reason, action="replace",
                      error=f"replica {replica.replica_id}",
                      role=getattr(replica, "role", "unified"))
        bb = blackbox.get_recorder()
        if bb is not None:
            # replay re-forces kill-reason retirements at this round
            # boundary (degraded retirements re-derive from the replayed
            # engine's own faults)
            bb.hop(kind="replica_retire", src=replica.replica_id,
                   reason=str(reason),
                   role=getattr(replica, "role", "unified"),
                   round=self._round)
        if self.auto_replace:
            with self._lock:
                short = sum(1 for r in self.replicas
                            if r.routable) < self._target
            if short:
                try:
                    # role-preserving replacement: a dead prefill
                    # replica respawns as prefill — a disaggregated
                    # fleet's role mix survives failover
                    self._spawn(restart=True, role=replica.role)
                except Exception as e:  # noqa: BLE001 — failover must
                    # still migrate the dead replica's work even when
                    # the replacement cannot be built (digest mismatch,
                    # allocation failure): survivors take it, or total
                    # refusal resolves it 'error' — never stranded
                    if rec is not None:
                        rec.fault(kind="replica_spawn_failed",
                                  action="continue",
                                  error=f"{type(e).__name__}: {e}")
        with self._lock:
            owned = [fr for fr in self._live if fr.replica is replica]
        for fr in owned:
            cur = fr.current
            if cur is not None and cur.done and \
                    cur.finish_reason not in ("error", "rejected"):
                self._finalize_one(fr)   # finished before the fault
            else:
                self._migrate(fr, reason=reason, src=replica)
        return True

    def _migrate(self, fr, reason, src=None):
        """Resubmit one evacuated request's continuation (prompt +
        tokens generated so far) to a healthy replica — token-exact for
        greedy requests (migration.py). Budget-bounded; a continuation
        at the cache horizon finishes "length" exactly as it would have
        on the original replica. `src` makes the call idempotent per
        hop: the retire scan and a racing dispatch may both see the
        same dead hop, and whoever detaches it first wins."""
        with self._lock:
            if src is not None and fr.replica is not src:
                return               # this hop was already failed over
            src_id = (None if fr.replica is None
                      else fr.replica.replica_id)
            cur = fr.current
            fr._absorb()             # detach atomically with the check
        if cur is not None and not cur.done:
            cur._fail(f"replica {src_id} {reason}")
        if not self.migrate:
            self._finalize_one(fr, forced=(
                "error", f"replica {src_id} {reason}; migration disabled"))
            return
        fr.migrations += 1
        if fr.migrations > self.max_migrations:
            self._finalize_one(fr, forced=(
                "error", f"migration budget spent ({self.max_migrations}x)"))
            return
        if len(fr._prior) >= fr.max_tokens:
            self._finalize_one(fr, forced=("max_tokens", None))
            return
        if self._continuation_refused(fr.prompt + fr._prior) is not None:
            # the continuation cannot be re-admitted ANYWHERE in this
            # fleet — the cache horizon, or on a dense fleet the prefill
            # bucket (re-prefill cannot exceed it even though the dead
            # replica was already past prefill): deliver the tokens
            # generated so far, terminated "length", not "error"
            self._finalize_one(fr, forced=("length", None))
            return
        # the flow event that LINKS the halves of a migrated request:
        # the dead hop's spans end here, the resumed hop's QUEUED span
        # opens under the same trace id on the new replica's row
        telemetry.trace_flow_step(
            fr.trace_id, "MIGRATE", src=src_id, reason=str(reason),
            migration=fr.migrations, tokens_so_far=len(fr._prior))
        bb = blackbox.get_recorder()
        if bb is not None:
            bb.hop(kind="migrate", request_id=fr.request_id,
                   trace_id=fr.trace_id, src=src_id,
                   reason=str(reason), migration=fr.migrations,
                   tokens_so_far=len(fr._prior), round=self._round)
        self._dispatch(fr, continuation=True)
        if fr.replica is not None:
            self.metrics.on_migration(request_id=fr.request_id,
                                      src=src_id,
                                      dst=fr.replica.replica_id)
        else:                        # total refusal: _dispatch resolved it
            with self._lock:
                if fr in self._live:
                    self._live.remove(fr)

    def _continuation_refused(self, cont_prompt):
        """Engine-level admissibility of a migrated continuation — the
        ENGINE owns its admission rules (dense prefill bucket, paged
        horizon/pool capacity), so ask one live engine rather than
        re-deriving them here; the fleet is homogeneous (one factory).
        None = admissible (or nothing alive to ask — dispatch resolves
        that case)."""
        with self._lock:
            for r in self.replicas:
                if r.state != "dead":
                    return r.engine.validate_prompt(cont_prompt)
        return None

    # -------------------------------------------------------- completions
    def _observe_slo(self, fr):
        """Feed one FINALIZED request to the SLO window. Every
        finalization path must come through here (including _dispatch's
        total-refusal resolutions) — a continuation failing dispatch-
        side is exactly the client-visible error the error-rate target
        exists to burn on. `rejected` stays excluded: that is admission
        control doing its job, not a served request."""
        if self.slo_engine is not None and fr.finish_reason != "rejected":
            self.slo_engine.observe_request(fr)

    def _finalize_one(self, fr, forced=None):
        if forced is not None:
            fr._finalize(forced[0], error=forced[1])
        else:
            fr._finalize_from(fr.current)
        with self._lock:
            if fr in self._live:
                self._live.remove(fr)
        bb = blackbox.get_recorder()
        if bb is not None:
            # fleet-origin completion: the STITCHED output stream across
            # every hop — the digest window replay verifies against
            bb.complete(fr, origin="fleet", migrations=fr.migrations,
                        round=self._round)
        self._observe_slo(fr)

    def _finalize_completed(self):
        with self._lock:
            done = [fr for fr in self._live
                    if fr.current is not None and fr.current.done]
        for fr in done:
            self._finalize_one(fr)

    # ----------------------------------------------------------- scaling
    def _spawn(self, restart=False, role="unified"):
        replica = self.supervisor.spawn(role=role)
        with self._lock:
            self.replicas.append(replica)
        bb = blackbox.get_recorder()
        if bb is not None:
            bb.hop(kind="replica_spawn", dst=replica.replica_id,
                   role=role, restart=bool(restart), round=self._round)
        if restart:
            self.metrics.on_restart()
        return replica

    def _autoscale(self):
        """Elastic scale on live telemetry. With an SLO configured the
        signal is error-budget BURN RATE (`_autoscale_slo`); otherwise
        the original queue-depth heuristic. Either way, replicas done
        draining (scale-down or operator drain()) leave the rotation
        here with their metrics folded into the retired rollup."""
        with self._lock:
            drained = [r for r in self.replicas
                       if r.state == "draining" and r.drained()]
            for r in drained:
                self.replicas.remove(r)
                self._retired_metric_snaps.append(
                    r.scheduler.metrics.snapshot())
        for r in drained:
            r.engine.stop_metrics_server()
        with self._lock:
            live = [r for r in self.replicas if r.routable]
        if not live:
            return
        if self.slo_engine is not None:
            self._autoscale_slo(live)
            return
        if self.scale_up_queue_depth is None:
            return
        queued = sum(r.scheduler.queue_depth() for r in live)
        busy = sum(r.load() for r in live)
        if queued / len(live) > self.scale_up_queue_depth \
                and len(live) < self.max_replicas:
            self._target = len(live) + 1
            self._spawn()
            self.metrics.on_scale("up")
            self._idle_rounds = 0
        elif busy == 0 and len(live) > self.min_replicas:
            self._idle_rounds += 1
            if self._idle_rounds >= self.scale_down_idle_rounds:
                victim = max(live, key=lambda r: r.replica_id)
                victim.drain()
                self._target = len(live) - 1
                self.metrics.on_scale("down")
                self._idle_rounds = 0
        else:
            self._idle_rounds = 0

    def _autoscale_slo(self, live):
        """Burn-rate autoscale: the SLO engine's verdict — computed
        from what requests actually EXPERIENCED (TTFT/TPOT/errors) —
        replaces queue depth. Fast burn (the latency promise is being
        broken faster than the budget allows) spawns a replica, rate-
        limited by the policy's cooldown so one long breach grows the
        fleet stepwise; burn at/under the slow threshold for
        `scale_down_idle_rounds` consecutive rounds is sustained
        surplus — the newest replica drains (its accepted work still
        completes) and retires. Scaling in either direction never drops
        accepted work."""
        pol = self.slo_engine.policy
        verdict = self.slo_engine.evaluate()
        burn = verdict["burn_rate"]
        if self._scale_cooldown > 0:
            self._scale_cooldown -= 1
        if burn >= pol.fast_burn:
            self._surplus_rounds = 0
            if self._scale_cooldown == 0 and len(live) < self.max_replicas:
                self._target = len(live) + 1
                self._spawn()
                self.metrics.on_scale("up")
                self.slo_engine.journal_scale("up", verdict,
                                              replicas=len(live) + 1)
                self._scale_cooldown = pol.cooldown_rounds
        elif burn <= pol.slow_burn:
            self._surplus_rounds += 1
            if self._surplus_rounds >= self.scale_down_idle_rounds \
                    and len(live) > self.min_replicas:
                victim = max(live, key=lambda r: r.replica_id)
                victim.drain()
                self._target = len(live) - 1
                self.metrics.on_scale("down")
                self.slo_engine.journal_scale("down", verdict,
                                              replicas=len(live) - 1)
                self._surplus_rounds = 0
        else:
            self._surplus_rounds = 0

    # ------------------------------------------------------------- admin
    def health(self):
        """Fleet-level health view: per-replica /healthz payloads plus
        the rotation summary (what an external dashboard polls). Also
        the fleet exporter's /healthz payload — `status` drives the
        probe's HTTP code, and the SLO verdict (burn rate, attainment)
        rides along when a policy is configured."""
        with self._lock:
            reps = list(self.replicas)
        routable = sum(1 for r in reps if r.routable)
        out = {
            "status": "ok" if routable else "degraded",
            "replicas": [r.health() for r in reps],
            "routable": routable,
            "target_replicas": self._target,
            "policy": self.policy,
        }
        if self.slo_engine is not None:
            out.update(self.slo_engine.health())
        if self._alerts is not None:
            out.update(self._alerts.health())
        return out

    def start_metrics_server(self, port=0, host="127.0.0.1"):
        """Fleet-wide /metrics + /healthz exporter: ONE scrape carries
        every replica's gauges labeled `replica` plus coherent fleet
        sums (FleetRegistry) alongside the process-wide registry;
        /healthz serves `health()` (503 once nothing is routable).
        port=0 picks a free port."""
        if self._metrics_server is not None:
            return self._metrics_server
        self._metrics_server = telemetry.MetricsServer(
            registry=FleetRegistry(self), host=host, port=port,
            health_fn=self.health).start()
        return self._metrics_server

    def stop_metrics_server(self):
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None

    def export_trace(self, path):
        """Export ONE merged chrome trace of everything the fleet did
        while the host profiler was recording: each replica's request
        lifecycle spans and scheduler slices sit on their own named
        process row (pid = replica_id + 1 — dead replicas keep their
        row, that is where a migrated request's first half lives), the
        router's DISPATCH/MIGRATE flow steps on row 0, and one flow arrow
        per request linking its spans across replicas."""
        meta = [{"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
                 "ts": 0, "args": {"name": "fleet-router"}}]
        for rid in range(self.supervisor.spawned):
            meta.append({"ph": "M", "name": "process_name",
                         "pid": rid + 1, "tid": 0, "ts": 0,
                         "args": {"name": f"replica-{rid}"}})
        return profiler.export_chrome_tracing(path, extra_events=meta)

    def drain(self):
        """Stop admitting fleet-wide; accepted work runs to completion
        (drive run() until it returns 0)."""
        for r in self._rotation():
            if r.state in ("ok", "draining"):
                r.drain()

    def shutdown(self, max_rounds=None):
        """drain() + drive to empty + stop every exporter (replicas'
        and the fleet-wide one)."""
        self.drain()
        rounds = self.run(max_rounds=max_rounds)
        for r in self._rotation():
            r.engine.stop_metrics_server()
        self.stop_metrics_server()
        return rounds

    def reset_metrics(self):
        """Fresh fleet + per-replica tallies (the bench builds one
        fleet and measures each load point separately). Only valid on
        an idle fleet — a new Scheduler per replica would strand
        in-flight work."""
        if self.outstanding():
            raise RuntimeError("reset_metrics on a non-idle fleet")
        self.metrics = FleetMetrics()
        with self._lock:
            self._retired_metric_snaps = []
        for r in self._rotation():
            r.renew_scheduler()
        if self.slo_engine is not None:
            self.slo_engine.reset()

    def retired_metric_snapshots(self):
        """Final ServingMetrics snapshots of replicas retired (killed,
        degraded-replaced, or drained away) since the last
        reset_metrics() — a fleet-wide rollup must include the work
        they completed before leaving the rotation."""
        with self._lock:
            return list(self._retired_metric_snaps)

    def metric_view(self):
        """(live non-dead replicas, retired snapshots) captured in ONE
        lock acquisition — the fleet exporter sums counters over both,
        and a replica retiring between two separate reads would be
        counted twice (or dropped), turning a monotonic counter into a
        sawtooth that rate() misreads as a reset."""
        with self._lock:
            return ([r for r in self.replicas if r.state != "dead"],
                    list(self._retired_metric_snaps))
