"""Disaggregated prefill/decode fleet: role-specialized replicas with
block-level KV handoff.

A unified replica interleaves chunked prefill with decode waves, so a
long prompt's admission steals rounds from every decoding lane on that
replica. Disaggregation splits the fleet by ROLE: prefill replicas run
ONLY the chunked-prefill program (their decode program is never even
compiled — jit is lazy and a pure-prefill replica never dispatches a
wave), decode replicas run ONLY decode waves, and the seam between them
is a **block-level KV transfer**, not recompute: the prefill replica
exports its populated per-layer KV blocks (digest-sealed —
`PagedServingEngine.export_slot_kv`), the router hands the payload to a
decode replica, and that replica's admission imports the blocks and
arms the slot directly (`import_handoff`). A handoff therefore costs
bytes proportional to the prompt's K/V, never a second prefill — the
FusionStitching principle (memory movement, not compute, is the cost to
engineer) applied at fleet scale, and the decode-side compile/program
count proves it: a handed-off request runs ZERO prefill-chunk programs
on the decode replica.

The handoff rides the token-exact migration machinery (migration.py):
the prefill hop's first token is absorbed into the fleet request's
stitched stream, the continuation (prompt + first token) dispatches
with the payload attached, and the decode replica's slot arms at
exactly the position/token a single-replica run would hold — greedy
output is bitwise-identical. A payload that fails its digest check is
REFUSED (the request fails, request-isolated: decoding over corrupt
K/V would silently produce wrong tokens), and a failed export falls
back to plain migration-by-recompute, budget-bounded.

Multi-tenant QoS (qos.py) layers on top: the shared QoSManager rides
every replica's scheduler (weighted-fair admission under pool
pressure, priority-chosen preemption victims), tenant priorities
resolve at fleet admission, and per-tenant SLO windows are fed from
fleet-level finalizations.
"""
from ...utils import telemetry
from .. import blackbox
from ..scheduler import ROLES  # noqa: F401  (re-exported convenience)
from .migration import FleetRequest
from .qos import as_manager
from .router import FleetRouter


class DisaggFleetRouter(FleetRouter):
    """FleetRouter over a role-specialized rotation.

    engine_factory: as FleetRouter — every replica (either role) is
        built from the same factory and digest-verified; role is a
        SCHEDULING specialization, not a different binary.
    prefill_replicas / decode_replicas / unified_replicas: the initial
        role mix. At least one prefill-capable (prefill or unified) and
        one decode-capable replica are required, or work could be
        accepted that no replica can ever finish.
    qos: a QoSManager, or an iterable of Tenants (qos.py). Shared by
        every replica's scheduler; None = single-tenant behavior.
    Remaining kwargs as FleetRouter (policy, migrate, slo, ...).
    """

    def __init__(self, engine_factory, prefill_replicas=1,
                 decode_replicas=1, unified_replicas=0, qos=None,
                 scheduler_kwargs=None, **kw):
        roles = (["prefill"] * int(prefill_replicas)
                 + ["decode"] * int(decode_replicas)
                 + ["unified"] * int(unified_replicas))
        if not any(r in ("prefill", "unified") for r in roles):
            raise ValueError("fleet needs at least one prefill-capable "
                             "replica (prefill or unified)")
        if not any(r in ("decode", "unified") for r in roles):
            raise ValueError("fleet needs at least one decode-capable "
                             "replica (decode or unified)")
        self.qos = as_manager(qos)
        scheduler_kwargs = dict(scheduler_kwargs or {})
        if self.qos is not None:
            # ONE manager across the rotation: weights and SLO windows
            # are fleet-global even though each scheduler computes its
            # own in-flight census
            scheduler_kwargs.setdefault("qos", self.qos)
        super().__init__(engine_factory, replicas=len(roles),
                         roles=roles, scheduler_kwargs=scheduler_kwargs,
                         **kw)

    # ---------------------------------------------------------- admission
    def submit(self, request=None, **kw):
        fr = request if request is not None else FleetRequest(**kw)
        if fr.priority is None and self.qos is not None:
            # tenant rank resolves ONCE, at fleet admission, and then
            # rides _submit_kwargs through every hop
            fr.priority = self.qos.priority(fr.tenant)
        return super().submit(request=fr)

    # ---------------------------------------------------------- the loop
    def step(self):
        """One fleet round, plus the disaggregation seam: pick up every
        prefill replica's completed handoffs and dispatch them to
        decode replicas, then refresh the per-tenant SLO windows."""
        super().step()
        self._pickup_handoffs()
        if self.qos is not None:
            self.qos.evaluate()
        return self.outstanding()

    def _pickup_handoffs(self):
        with self._step_lock:
            for replica in self._rotation():
                if replica.state == "dead":
                    continue
                take = getattr(replica.scheduler, "take_handoffs", None)
                if take is None:
                    continue
                for req, payload in take():
                    fr = self._owner_of(req)
                    if fr is None:
                        continue     # finalized concurrently (timeout)
                    if payload is None:
                        # export failed: fall back to recompute — the
                        # classic migration path, budget-bounded
                        self._migrate(fr, reason="handoff export failed",
                                      src=replica)
                    else:
                        self._handoff(fr, payload, src=replica)

    def _owner_of(self, req):
        with self._lock:
            for fr in self._live:
                if fr.current is req:
                    return fr
        return None

    def _handoff(self, fr, payload, src):
        """Move one prefilled request to a decode replica carrying its
        KV payload. Mirrors _migrate's absorb-and-redispatch shape but
        does NOT spend the migration budget — a handoff is the planned
        fast path, not a fault recovery — and the continuation imports
        blocks instead of re-prefilling."""
        with self._lock:
            if fr.replica is not src:
                return               # hop already failed over elsewhere
            src_id = src.replica_id
            fr._absorb()             # bank the prefill's first token
        if len(fr._prior) >= fr.max_tokens:
            self._finalize_one(fr, forced=("max_tokens", None))
            return
        if self._continuation_refused(fr.prompt + fr._prior) is not None:
            self._finalize_one(fr, forced=("length", None))
            return
        telemetry.trace_flow_step(
            fr.trace_id, "HANDOFF", src=src_id,
            blocks=len(payload["manifest"]), nbytes=payload["nbytes"],
            tokens_so_far=len(fr._prior))
        bb = blackbox.get_recorder()
        if bb is not None:
            bb.hop(kind="handoff", request_id=fr.request_id,
                   trace_id=fr.trace_id, src=src_id,
                   digest=payload["digest"],
                   blocks=len(payload["manifest"]),
                   nbytes=payload["nbytes"],
                   tokens_so_far=len(fr._prior), round=self._round)
        fr._handoff_payload = payload
        try:
            self._dispatch(fr, continuation=True)
        finally:
            # one-shot: whatever happened, a LATER redispatch (e.g. a
            # migration after the decode replica dies) must replay by
            # recompute — the payload's blocks belong to the hop that
            # imported them (or to nobody, if dispatch failed)
            fr._handoff_payload = None
        if fr.replica is not None:
            self.metrics.on_handoff(
                request_id=fr.request_id, src=src_id,
                dst=fr.replica.replica_id,
                blocks=len(payload["manifest"]),
                nbytes=payload["nbytes"])
        else:                        # total refusal: _dispatch resolved it
            with self._lock:
                if fr in self._live:
                    self._live.remove(fr)

    # -------------------------------------------------------- completions
    def _observe_slo(self, fr):
        super()._observe_slo(fr)
        if self.qos is not None:
            self.qos.observe(fr)

    # ------------------------------------------------------------- admin
    def health(self):
        out = super().health()
        roles = {"prefill": 0, "decode": 0, "unified": 0}
        for r in out["replicas"]:
            roles[r.get("role", "unified")] = \
                roles.get(r.get("role", "unified"), 0) + 1
        out["roles"] = roles
        if self.qos is not None:
            out["tenants"] = self.qos.summary()
        return out
