"""paddle_tpu.serving.fleet — multi-replica serving with
prefix-affinity routing, token-exact failover, and elastic scale.

One `PagedServingEngine` is chaos-proven but still a single point of
failure; the fleet is the layer that makes `serving/` a SERVICE. A
`FleetRouter` fronts N replicas:

    from paddle_tpu.serving import fleet
    router = fleet.FleetRouter(lambda: PagedServingEngine(model, ...),
                               replicas=3)
    req = router.submit(prompt=[1, 2, 3], max_tokens=32)
    router.run()                     # drives every replica's wave loop
    req.output_tokens

Routing keys off the prefix cache the paged engine already maintains
(the BlockPool's sha256 chain hashes over full prompt blocks), so a
shared-system-prompt cohort lands where its K/V blocks already live;
a killed or degraded replica's in-flight requests are resubmitted
(prompt + tokens so far) and finish token-identically on a survivor
(proven by `scripts/chaos_serving.py --scenarios replica_failover`);
and the rotation grows/shrinks against live queue-depth telemetry with
digest-verified warm starts. See docs/serving.md "Serving fleet".
"""
from .disagg import DisaggFleetRouter
from .metrics import FleetMetrics, FleetRegistry
from .migration import FleetRequest
from .qos import QoSManager, Tenant
from .replica import Replica, ReplicaSupervisor, state_digest
from .router import FleetRouter

__all__ = ["FleetRouter", "DisaggFleetRouter", "FleetRequest",
           "FleetMetrics", "FleetRegistry", "QoSManager", "Tenant",
           "Replica", "ReplicaSupervisor", "state_digest"]
