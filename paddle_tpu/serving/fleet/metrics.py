"""Fleet metrics: replica states, routing decisions, migrations and
restarts.

Same two-sink discipline as serving/metrics.py: the typed process-wide
registry (docs/observability.md catalogs the names below) feeds
/metrics, while a `FleetMetrics` instance aggregates per-router tallies
for bench rows (`scripts/bench_serving.py --replicas` serializes
`snapshot()` per load point).
"""
import threading

from ...utils import flight_recorder, telemetry

_REPLICAS = telemetry.gauge(
    "fleet_replicas", "Replicas in the router's rotation by state",
    labelnames=("state",))
_MIGRATIONS = telemetry.counter(
    "fleet_migrations_total",
    "In-flight requests resubmitted (prompt + tokens generated so far) "
    "from a dead or degraded replica to a healthy one — token-exact for "
    "greedy requests (the preemption-by-recompute contract)")
_ROUTED = telemetry.counter(
    "fleet_routed_total",
    "Requests routed by decision policy: affinity (prefix-cache blocks "
    "matched on the chosen replica), least_loaded (no replica held the "
    "prefix), or round_robin (A/B baseline policy)",
    labelnames=("policy",))
_RESTARTS = telemetry.counter(
    "fleet_replica_restarts_total",
    "Replacement replicas spawned after a kill/degradation (warm start: "
    "weights digest-checked against the fleet's reference state)")
_DISPATCH_RETRIES = telemetry.counter(
    "fleet_dispatch_retries_total",
    "Dispatch attempts rerouted to the next candidate replica after a "
    "dispatch fault or a replica-side rejection — an accepted request "
    "is never lost to a single bad hand-off")
_ROLES = telemetry.gauge(
    "fleet_replica_role", "Replicas in the rotation by disaggregation "
    "role (prefill / decode / unified)", labelnames=("role",))
_HANDOFF_BLOCKS = telemetry.counter(
    "fleet_handoff_blocks_total",
    "KV blocks shipped prefill->decode via the block-level handoff "
    "path (digest-verified; the bytes-not-recompute transfer)")
_HANDOFF_BYTES = telemetry.counter(
    "fleet_handoff_bytes_total",
    "Device bytes shipped in block-level KV handoff payloads")


class FleetMetrics:
    """Per-router aggregation (the process-wide counters keep
    accumulating for /metrics; a fresh router — or a bench load point
    via `FleetRouter.reset_metrics()` — gets fresh tallies)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._routed = {}            # policy -> count
        self._migrations = 0
        self._restarts = 0
        self._dispatch_retries = 0
        self._rejected = 0
        self._kills = 0
        self._scale_ups = 0
        self._scale_downs = 0
        self._handoffs = 0
        self._handoff_blocks = 0
        self._handoff_bytes = 0

    # ---------------------------------------------------------- recording
    def on_routed(self, policy):
        _ROUTED.labels(policy=policy).inc()
        with self._lock:
            self._routed[policy] = self._routed.get(policy, 0) + 1

    def on_migration(self, request_id=None, src=None, dst=None):
        _MIGRATIONS.inc()
        with self._lock:
            self._migrations += 1
        rec = flight_recorder.get_recorder()
        if rec is not None:
            rec.fault(kind="replica_migration", action="resubmitted",
                      request_id=request_id,
                      error=f"replica {src} -> {dst}")

    def on_handoff(self, request_id=None, src=None, dst=None, blocks=0,
                   nbytes=0):
        """One block-level prefill->decode KV handoff dispatched. The
        journal event's kind is distinct from replica_migration so the
        runlog's fleet table can count bytes-moved handoffs separately
        from recompute migrations."""
        _HANDOFF_BLOCKS.inc(blocks)
        _HANDOFF_BYTES.inc(nbytes)
        with self._lock:
            self._handoffs += 1
            self._handoff_blocks += blocks
            self._handoff_bytes += nbytes
        rec = flight_recorder.get_recorder()
        if rec is not None:
            rec.fault(kind="replica_handoff", action="resubmitted",
                      request_id=request_id,
                      error=f"replica {src} -> {dst} "
                            f"({blocks} blocks, {nbytes} bytes)",
                      blocks=int(blocks), nbytes=int(nbytes))

    def on_restart(self):
        _RESTARTS.inc()
        with self._lock:
            self._restarts += 1

    def on_dispatch_retry(self):
        _DISPATCH_RETRIES.inc()
        with self._lock:
            self._dispatch_retries += 1

    def on_rejected(self):
        """One request refused fleet-wide. Counted HERE, once per
        request — the per-replica serving counters tick once per
        candidate walked, so summing them across the rotation would
        inflate the shed count by up to the replica count."""
        with self._lock:
            self._rejected += 1

    def on_kill(self):
        with self._lock:
            self._kills += 1

    def on_scale(self, direction):
        with self._lock:
            if direction == "up":
                self._scale_ups += 1
            else:
                self._scale_downs += 1

    def publish_states(self, replicas, dead_total=0):
        """Export the rotation's state census (called once per fleet
        step). Every known state is set — including back to 0 — so a
        replica leaving a state is visible, not sticky. Dead replicas
        leave the rotation at retirement, so the `dead` series carries
        the router's CUMULATIVE kill/degrade count instead (a census of
        the rotation alone could never show a nonzero dead bucket)."""
        counts = {"ok": 0, "degraded": 0, "draining": 0,
                  "dead": dead_total}
        roles = {"prefill": 0, "decode": 0, "unified": 0}
        for r in replicas:
            counts[r.state] = counts.get(r.state, 0) + 1
            role = getattr(r, "role", "unified")
            roles[role] = roles.get(role, 0) + 1
        for state, n in counts.items():
            _REPLICAS.labels(state=state).set(n)
        for role, n in roles.items():
            _ROLES.labels(role=role).set(n)

    # ---------------------------------------------------------- reporting
    def snapshot(self):
        """Router-level tallies for bench rows: routing mix + affinity
        hit rate, migrations, restarts, rebalance (scale) events."""
        with self._lock:
            routed = dict(self._routed)
            total = sum(routed.values())
            return {
                "routed": routed,
                "routed_total": total,
                "affinity_hit_rate": (routed.get("affinity", 0) / total
                                      if total else None),
                "migrations": self._migrations,
                "handoffs": self._handoffs,
                "handoff_blocks": self._handoff_blocks,
                "handoff_bytes": self._handoff_bytes,
                "rejected": self._rejected,
                "replica_kills": self._kills,
                "replica_restarts": self._restarts,
                "dispatch_retries": self._dispatch_retries,
                "rebalances": self._scale_ups + self._scale_downs,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
            }


class FleetRegistry:
    """Fleet-wide /metrics view: a duck-typed telemetry registry over a
    FleetRouter, served by `FleetRouter.start_metrics_server()`.

    The process-wide registry cannot distinguish replicas — every
    engine's `serving_*` gauges overwrite one series. This facade
    builds, per scrape, a fresh registry of per-replica gauges labeled
    `replica` (queue depth, active slots, pool occupancy, health state)
    plus fleet-summed counters that stay COHERENT across kill/replace
    cycles (retired replicas' final metric snapshots are folded in, so
    work done before a kill never vanishes from the totals), and
    appends the process-wide exposition after it. Building per scrape
    also means a replica leaving the rotation drops its series instead
    of freezing at its last value.
    """

    def __init__(self, router):
        self._router = router

    def _build(self):
        reg = telemetry.Registry()
        depth = reg.gauge(
            "fleet_replica_queue_depth",
            "Requests queued on each replica", ("replica",))
        slots = reg.gauge(
            "fleet_replica_slots_active",
            "Slots decoding on each replica", ("replica",))
        used = reg.gauge(
            "fleet_replica_cache_blocks_used",
            "KV blocks referenced by live requests, per replica",
            ("replica",))
        total = reg.gauge(
            "fleet_replica_cache_blocks_total",
            "Usable KV blocks in each replica's pool", ("replica",))
        state = reg.gauge(
            "fleet_replica_state",
            "1 for each replica's current health state (a replica "
            "changing state moves the 1 between series)",
            ("replica", "state"))
        router = self._router
        # one atomic capture: a replica mid-retirement lands in exactly
        # one of the two lists, keeping the summed counters monotonic
        reps, retired = router.metric_view()
        for r in reps:
            h = r.health()
            lbl = str(r.replica_id)
            depth.labels(replica=lbl).set(h.get("queue_depth", 0))
            slots.labels(replica=lbl).set(h.get("slots_active", 0))
            if "cache_blocks_used" in h:
                used.labels(replica=lbl).set(h["cache_blocks_used"])
                total.labels(replica=lbl).set(h["cache_blocks_total"])
            state.labels(replica=lbl, state=h.get("status", "ok")).set(1)
        snaps = [r.scheduler.metrics.snapshot() for r in reps] + retired
        reg.counter(
            "fleet_tokens_generated_total",
            "Tokens generated across the whole fleet — live rotation "
            "plus replicas retired since the last reset, so a "
            "kill/replace cycle never loses counted work").inc(
            sum(s["tokens_generated"] for s in snaps))
        reg.counter(
            "fleet_requests_completed_total",
            "Requests completed across the whole fleet (same retired-"
            "replica folding as the token counter)").inc(
            sum(s["requests_completed"] for s in snaps))
        return reg

    # -- duck-typed registry surface (what make_metrics_handler calls) --
    def render_prometheus(self, include_monitor=True):
        return (self._build().render_prometheus(include_monitor=False)
                + telemetry.REGISTRY.render_prometheus(include_monitor))

    def snapshot(self, include_monitor=True):
        out = telemetry.REGISTRY.snapshot(include_monitor)
        out["metrics"].update(
            self._build().snapshot(include_monitor=False)["metrics"])
        return out
