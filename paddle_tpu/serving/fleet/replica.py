"""Replica supervision: one serving engine + scheduler per replica,
spawned from a factory and admitted into rotation only after a
state-handoff digest check.

The fleet's token-exact migration contract (a request killed mid-stream
finishes elsewhere with bitwise-identical output) rests on every
replica serving EXACTLY the same weights. The supervisor enforces it
the way the exact-resume layer does for training checkpoints: a sha256
digest over the engine's functional state, banked from the first
replica and verified for every later spawn — a factory that drifted
(different seed, stale checkpoint, half-updated weights) is refused at
spawn, not discovered as token divergence in production.
"""
import hashlib

import jax
import numpy as np

from ..scheduler import Scheduler


def state_digest(engine):
    """sha256 over the engine's functional state (params + buffers, in
    pytree-leaf order — deterministic for one model structure). The
    serving analog of the checkpoint manifest's per-file digests."""
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves((engine._params,
                                           engine._buffers)):
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


class Replica:
    """One engine + scheduler in the fleet's rotation.

    state: ok | degraded | draining | dead. `degraded` is adopted from
    the scheduler (the engine's own resilience layer decides it); the
    router reacts by replacing the replica. `dead` is terminal — a
    killed replica's engine is never called again.
    """

    def __init__(self, replica_id, engine, scheduler_kwargs=None,
                 role="unified"):
        self.replica_id = int(replica_id)
        self.engine = engine
        # disaggregated fleets (fleet/disagg.py) specialize replicas:
        # "prefill" runs only chunked prefill and exports KV handoffs,
        # "decode" imports them and only decodes, "unified" does both
        self.role = str(role)
        self._scheduler_kwargs = dict(scheduler_kwargs or {})
        self.scheduler = Scheduler(engine, role=self.role,
                                   **self._scheduler_kwargs)
        # chrome-trace process row: the router's merged trace shows
        # each replica's request spans + scheduler slices on its own
        # pid row (0 stays the router/host row)
        self.scheduler.trace_pid = self.replica_id + 1
        self._killed = False

    def renew_scheduler(self):
        """Fresh Scheduler (fresh ServingMetrics) over the same warm
        engine — the bench measures each load point separately. Only
        valid idle: a replaced scheduler would strand accepted work."""
        if self.scheduler.in_flight() or self.scheduler.queue_depth():
            raise RuntimeError("renew_scheduler on a busy replica")
        self.scheduler = Scheduler(self.engine, role=self.role,
                                   **self._scheduler_kwargs)
        self.scheduler.trace_pid = self.replica_id + 1

    def accepts(self, needs_prefill):
        """Role gate for routing: a unified replica takes anything; a
        prefill replica takes only fresh (prefill-needing) work; a
        decode replica takes only block-level handoff continuations."""
        if self.role == "unified":
            return True
        return self.role == ("prefill" if needs_prefill else "decode")

    @property
    def state(self):
        if self._killed:
            return "dead"
        if self.scheduler.degraded:
            return "degraded"
        if self.scheduler.draining:
            return "draining"
        return "ok"

    @property
    def routable(self):
        """May new work be routed here? Draining replicas finish what
        they accepted but take nothing new."""
        return self.state == "ok"

    def load(self):
        """Routing load score: requests in slots + waiting in queue."""
        return self.scheduler.in_flight() + self.scheduler.queue_depth()

    def health(self):
        """The /healthz payload (status, queue_depth,
        cache_blocks_used/total on a paged engine) — what the router
        watches; an external LB reads the same dict over HTTP."""
        h = self.engine._health()
        h["replica_id"] = self.replica_id
        h["role"] = self.role
        if self._killed:
            h["status"] = "dead"
        return h

    def affinity_hashes(self, hashes):
        """Prefix-affinity score from precomputed chain hashes: cached
        leading prompt blocks this replica could serve (0 on a dense
        engine — no block pool). The router hashes a prompt ONCE per
        admission and scores every replica by pool lookups (chain
        hashes are content-only, so one prompt's walk is valid against
        every pool)."""
        pool = getattr(self.engine, "block_pool", None)
        return 0 if pool is None else pool.peek_prefix_hashes(hashes)

    def drain(self):
        self.scheduler.drain()

    def drained(self):
        """True when a draining replica has resolved every accepted
        request (safe to retire from rotation)."""
        return (self.scheduler.in_flight() == 0
                and self.scheduler.queue_depth() == 0)

    def kill(self):
        """Simulated crash: mark the replica dead, stop its exporter,
        and return the accepted-but-unresolved requests it stranded
        (informational — the router migrates from its own registry,
        not from a dead replica's bookkeeping). Engine state is never
        touched again — a real dead process has none."""
        self._killed = True
        harvested = self.scheduler.evacuate()
        self.engine.stop_metrics_server()
        return harvested

    def __repr__(self):
        return (f"Replica(id={self.replica_id}, state={self.state}, "
                f"load={self.load() if not self._killed else '-'})")


class ReplicaSupervisor:
    """Owns replica lifecycle: spawn (with the digest handoff check),
    replacement counting, and id allocation. The ROUTER decides *when*
    to spawn/kill/drain; the supervisor guarantees *what* enters the
    rotation is a faithful replica."""

    def __init__(self, engine_factory, scheduler_kwargs=None,
                 verify_state=True):
        self.engine_factory = engine_factory
        self.scheduler_kwargs = dict(scheduler_kwargs or {})
        self.verify_state = bool(verify_state)
        self.reference_digest = None
        self._next_id = 0

    @property
    def spawned(self):
        """Replica ids handed out so far (dead ones included) — the
        trace exporter names one chrome process row per id ever
        spawned, so a killed replica's spans stay labeled."""
        return self._next_id

    def spawn(self, role="unified"):
        """Build one replica (optionally role-specialized — the router
        preserves a dead replica's role on replacement, so a killed
        prefill replica respawns as prefill). The first spawn banks the
        fleet's reference state digest; every later spawn must match it
        (warm replacement serves the SAME weights or it does not
        serve)."""
        engine = self.engine_factory()
        if self.verify_state:
            digest = state_digest(engine)
            if self.reference_digest is None:
                self.reference_digest = digest
            elif digest != self.reference_digest:
                raise RuntimeError(
                    "replica state-handoff mismatch: factory produced "
                    f"weights with digest {digest[:12]}…, fleet "
                    f"reference is {self.reference_digest[:12]}… — a "
                    "replacement replica must serve identical state "
                    "(token-exact migration depends on it)")
        replica = Replica(self._next_id, engine,
                          scheduler_kwargs=self.scheduler_kwargs,
                          role=role)
        self._next_id += 1
        return replica
