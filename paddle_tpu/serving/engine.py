"""ServingEngine: slot-based continuous batching over the causal-LM
decode paths (nlp/gpt.py, nlp/llama.py).

The engine owns `num_slots` decode slots backed by ONE batched KV cache
[num_slots, kv_heads, max_len, head_dim] per layer and exactly TWO
compiled programs, both with fully static shapes so XLA compiles each
once for the life of the engine (compile-once discipline — the whole
request stream reuses the same executable):

  * decode wave — one token for every slot at once. Per-slot state rides
    as vectors: position [S] (each slot at its own depth — decode_step's
    position-vector path), active mask [S] (retired slots are frozen
    with `where`, their lanes compute and are discarded; that is the
    price of fixed shapes and it is the right trade in the
    memory-bandwidth-bound decode regime, where the [S,...] cache stream
    dominates and a masked lane adds nothing).
  * prefill — one slot's prompt, padded to a fixed bucket, through the
    model's prompt-phase forward (`prefill`), then the slot's cache
    region is spliced into the batched cache with dynamic_update_slice
    at a TRACED slot index (so one program serves every slot). The
    frontier logits yield the request's first token: TTFT is paid at
    admission, not at the next wave.

Retire-and-refill happens BETWEEN waves by rewriting the per-slot
vectors — in-flight decodes never stall and never recompile.

Slot bookkeeping (positions, tokens, flags) is host-authoritative:
five tiny [S] uploads per wave instead of device round-trips, and the
next-token pull each wave is the one unavoidable sync (the tokens are
the product being streamed).
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..utils import chaos, telemetry

HEALTH_STATES = ("ok", "degraded", "draining")

# program-cost memo keyed by engine shape signature: every fleet
# replica built from one factory shares a single lowering-level cost
# analysis instead of paying one per engine (the fleet tests spawn
# dozens of engines over one model)
_PROGRAM_COST_CACHE = {}


def _infer_cache_dtype(params):
    """Majority element dtype of the params — a bf16 model gets bf16 KV
    caches (halves the per-token HBM stream that bounds decode), an f32
    model keeps f32 (same policy as nlp.gpt.generate's cached path)."""
    # normalize to np.dtype keys: leaf.dtype is an np.dtype, and probing
    # a dict of those with the jnp scalar TYPE hashes differently even
    # though == compares true
    f32 = np.dtype(jnp.float32)
    floats = {np.dtype(jnp.bfloat16), np.dtype(jnp.float16), f32}
    counts = {}
    for leaf in jax.tree_util.tree_leaves(params):
        dt = np.dtype(leaf.dtype)
        if dt in floats:
            counts[dt] = counts.get(dt, 0) + int(np.prod(leaf.shape))
    low = {d: c for d, c in counts.items() if d != f32}
    if low and sum(low.values()) > counts.get(f32, 0):
        return max(low, key=low.get)
    return jnp.float32


def _raw(x):
    return x._data if isinstance(x, Tensor) else x


def _filter_top_k_top_p(lo, top_k, top_p):
    """Per-ROW top-k / nucleus filtering over already-temperature-scaled
    logits [S, V] with traced per-slot knobs top_k [S] int32 (<=0 = off)
    and top_p [S] f32 (>=1 = off). Same SEQUENTIAL semantics as
    nn.decode.top_k_top_p_filtering — top-k first (kth-value threshold,
    ties kept), then top-p over the RENORMALIZED top-k survivors (keep
    the smallest prefix whose cumulative prob reaches p, best token
    always kept) — vectorized so every slot carries its own knobs in
    ONE compiled program, with one sort serving both stages. Disabled
    rows pass through bitwise-identical (`where(True, lo, _)` is the
    identity), which is what keeps the pre-existing fixed-seed sampling
    streams unchanged."""
    v = lo.shape[-1]
    sort_idx = jnp.argsort(-lo, axis=-1)
    sorted_lo = jnp.take_along_axis(lo, sort_idx, axis=-1)
    kth = jnp.take_along_axis(
        sorted_lo, (jnp.clip(top_k, 1, v) - 1)[:, None], axis=-1)
    in_k = (sorted_lo >= kth) | (top_k <= 0)[:, None]   # sorted space
    # nucleus over the top-k-FILTERED distribution (softmax of the
    # masked row renormalizes it), exactly like applying the reference
    # filters back to back
    probs = jax.nn.softmax(
        jnp.where(in_k, sorted_lo, jnp.float32(-1e9)), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (((cum - probs) < top_p[:, None])
                   | (top_p >= 1.0)[:, None]).at[:, 0].set(True)
    keep_sorted &= in_k
    inv = jnp.argsort(sort_idx, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
    return jnp.where(keep, lo, jnp.float32(-1e9))


def _select_wave_tokens(lo, tok, pos, active, sample, temps, top_k,
                        top_p, bias, poison, key):
    """The decode wave's token-selection tail, shared by the dense AND
    paged programs — the paged/dense token-parity contract depends on
    this math staying identical, so it lives exactly once. The
    speculative verify tail reuses the same pieces position-by-position
    (engine subclasses never reimplement the selection math).

    Scenario surface: `bias` [S, V] is the per-request logit-bias /
    token-mask hook (0 = untouched; -1e9 = forbidden — constrained/JSON
    decoding uploads a fresh mask row per wave), `top_k`/`top_p` are
    per-slot sampling knobs applied after temperature. Greedy lanes take
    argmax over the BIASED logits (top-k/p cannot change an argmax).

    poison is all-False in production; the chaos harness sets a lane to
    inject NaN logits WITHOUT a second compiled program. The fused
    non-finite sentinel (the jit.TrainStep isfinite pattern) rides home
    as one [S] bool with the tokens — no extra device sync; a poisoned
    lane is frozen in-program and retired by the scheduler with
    finish_reason "error". Inactive (or poisoned) lanes keep their
    token and position via where — fixed shapes, no recompiles."""
    lo = jnp.where(poison[:, None], jnp.float32(jnp.nan), lo + bias)
    finite = jnp.all(jnp.isfinite(lo), axis=-1)
    greedy = jnp.argmax(lo, axis=-1).astype(jnp.int32)
    scaled = lo / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(
        key, _filter_top_k_top_p(scaled, top_k, top_p),
        axis=-1).astype(jnp.int32)
    nxt = jnp.where(sample, sampled, greedy)
    ok = active & finite
    nxt = jnp.where(ok, nxt, tok)
    new_pos = jnp.where(ok, pos + 1, pos)
    return nxt, new_pos, finite


def _select_first_token(lo, sample, temp, top_k, top_p, bias, key):
    """The prefill programs' first-token selection ([V] frontier logits
    -> token), shared by the dense AND paged chunked programs — same
    parity contract as _select_wave_tokens: this math lives exactly
    once. Takes the admitted request's full sampling params (the first
    token must obey the same temperature/top-k/top-p/bias as the decode
    tail will)."""
    lo = lo + bias
    greedy = jnp.argmax(lo).astype(jnp.int32)
    scaled = (lo / jnp.maximum(temp, 1e-6))[None, :]
    sampled = jax.random.categorical(
        key, _filter_top_k_top_p(scaled, top_k[None], top_p[None])[0]
    ).astype(jnp.int32)
    return jnp.where(sample, sampled, greedy)


class ServingEngine:
    """Fixed-shape batched decode executor. The Scheduler decides WHICH
    request occupies which slot and when; the engine only knows slots.

    model: a causal LM exposing prefill / decode_step / init_cache
        (GPTForPretraining, LlamaForCausalLM).
    num_slots: concurrent sequences per wave.
    max_len: per-slot cache horizon (prompt + generated tokens).
    prefill_len: prompt padding bucket (<= max_len; default max_len).
        One bucket => one prefill compile for every prompt length.
    jit_compile=False runs both programs uncompiled per call (the
        inference Config's ir_optim=False analog) — for debugging;
        decode_compiles stays 0 on that path.
    """

    def __init__(self, model, num_slots=4, max_len=256, prefill_len=None,
                 cache_dtype=None, jit_compile=True, seed=0):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        self.model = model
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.prefill_len = int(prefill_len or max_len)
        if self.prefill_len > self.max_len:
            raise ValueError(
                f"prefill_len {self.prefill_len} > max_len {self.max_len}")
        model.eval()
        self._params, self._buffers = model.functional_state()
        self.cache_dtype = (cache_dtype if cache_dtype is not None
                            else _infer_cache_dtype(self._params))
        self._caches = self._make_caches()
        # the one PRNG chain every sampled request on this engine draws
        # from — recorded (blackbox `run_start` harness / per-request
        # seed provenance) so a fresh engine built with the same seed
        # replays sampled streams token-exact
        self.seed = int(seed)
        self._key = jax.random.PRNGKey(seed)

        # host-authoritative per-slot state
        S = self.num_slots
        # vocab width: the logit-bias / token-mask rows are [V] uploads
        self.vocab_size = int(model.cfg.vocab_size)
        self.slot_active = [False] * S
        self.slot_pos = [0] * S        # next cache write position
        self.slot_tok = [0] * S        # token fed to the next wave
        self.slot_sample = [False] * S
        self.slot_temp = [1.0] * S
        # per-request scenario surface (all flow through the one shared
        # sampling tail, _select_wave_tokens): top-k / nucleus knobs and
        # a [S, V] additive logit-bias/token-mask matrix (0 = untouched,
        # -1e9 = forbidden). A slot with a DYNAMIC mask (a token_mask
        # callable refreshed per wave by the scheduler) is flagged so a
        # speculative engine clamps its draft span to 0 for that lane —
        # drafting ahead of a mask that depends on emitted tokens would
        # break exactness.
        self.slot_top_k = [0] * S
        self.slot_top_p = [1.0] * S
        self.slot_dynamic_mask = [False] * S
        self._slot_bias = np.zeros((S, self.vocab_size), np.float32)
        # device-resident copy of the bias matrix, re-uploaded only
        # when a row actually changes: the [S, V] upload would
        # otherwise ride EVERY wave of every engine (V can be 50k+),
        # and the common case is all-zeros. The wave programs never
        # donate it, so the same device array serves every wave.
        self._slot_bias_dev = None
        self._slot_bias_nonzero = [False] * S

        # admissions mid-prefill (slot -> engine-specific state): the
        # scheduler admits via begin_prefill and advances one
        # prefill_step per scheduling round, so a long admission can be
        # folded BETWEEN decode waves (the dense engine completes in one
        # step; the paged engine runs one chunk per step)
        self._pending_prefill = {}
        self.last_nonfinite_slots = []
        # paged engines report lanes whose next cache write could not be
        # backed by a block (pool exhausted) — the scheduler preempts
        # them; dense engines never starve
        self.last_starved_slots = []
        self.health_state = "ok"
        # the scheduler attaches its queue-depth probe here so /healthz
        # carries real load state (a router or LB reads ONE endpoint
        # instead of scraping /metrics); 0 until a scheduler attaches
        self._queue_depth_fn = None
        # optional dict-returning probe merged into /healthz (the
        # scheduler's SLO engine reports burn-rate state this way);
        # newest wins, like the queue probe
        self._health_probe_fn = None
        # slot -> (trace_id, trace_pid): the scheduler parks the
        # admitted request's trace context so engine-internal progress
        # (the paged engine's per-chunk prefill) can emit
        # request-correlated trace events
        self._slot_trace = {}
        self._program_costs_memo = None

        self._jit = bool(jit_compile)
        self._metrics_server = None
        self._build_programs()

    def _make_caches(self):
        return self.model.init_cache(self.num_slots, self.max_len,
                                     dtype=self.cache_dtype)

    # ---------------------------------------------------------- programs
    def _build_programs(self):
        model, L = self.model, self.max_len
        cache_dtype = self.cache_dtype

        def decode_wave(p, b, caches, tok, pos, active, sample, temps,
                        top_k, top_p, bias, poison, key):
            out, _ = model.functional_call(p, b, tok[:, None], caches,
                                           pos, method="decode_step")
            logits, new_caches = out
            lo = _raw(logits)[:, 0, :].astype(jnp.float32)
            nxt, new_pos, finite = _select_wave_tokens(
                lo, tok, pos, active, sample, temps, top_k, top_p, bias,
                poison, key)
            return nxt, new_pos, finite, new_caches

        def prefill(p, b, caches, prompt, prompt_len, slot, sample, temp,
                    top_k, top_p, bias, key):
            # frontier=prompt_len-1: the model applies its LM head to
            # that ONE position, not the whole padded bucket
            out, _ = model.functional_call(p, b, prompt[None, :],
                                           method="prefill", max_len=L,
                                           dtype=cache_dtype,
                                           frontier=prompt_len - 1)
            logits, slot_caches = out
            lo = _raw(logits)[0, 0].astype(jnp.float32)    # [V]
            first = _select_first_token(lo, sample, temp, top_k, top_p,
                                        bias, key)
            new_caches = []
            for (ck, cv), (sck, scv) in zip(caches, slot_caches):
                ck = jax.lax.dynamic_update_slice(
                    ck, _raw(sck).astype(ck.dtype), (slot, 0, 0, 0))
                cv = jax.lax.dynamic_update_slice(
                    cv, _raw(scv).astype(cv.dtype), (slot, 0, 0, 0))
                new_caches.append((ck, cv))
            return first, new_caches

        # raw closures + jit spec, kept for the compile-level audit
        # (tools/xprof lowers THE functions the engine serves — and can
        # re-jit a deliberately degraded copy for its injection test —
        # rather than a drifting reimplementation)
        self._decode_wave_fn = decode_wave
        self._prefill_fn = prefill
        self._program_donate_argnums = (2,)

        if self._jit:
            # donate the batched cache: the engine always replaces its
            # cache reference with the program output, so XLA may update
            # it in place — without this every wave would transiently
            # hold 2x the [S, Hkv, L, D] pair in HBM.
            # instrument_jit attributes XLA compile events to these
            # labels (xla_compiles_total{function=...}) — the
            # compile-once invariant as a live metric, not just the
            # _cache_size() test assertion.
            self._decode_wave = telemetry.instrument_jit(
                jax.jit(decode_wave,
                        donate_argnums=self._program_donate_argnums),
                "serving_decode_wave")
            self._prefill = telemetry.instrument_jit(
                jax.jit(prefill,
                        donate_argnums=self._program_donate_argnums),
                "serving_prefill")
        else:
            self._decode_wave = decode_wave
            self._prefill = prefill

    @property
    def decode_compiles(self):
        """Number of compiled decode-wave programs (the compile-once
        invariant: stays 1 across the whole request stream)."""
        return self._decode_wave._cache_size() if self._jit else 0

    @property
    def prefill_compiles(self):
        return self._prefill._cache_size() if self._jit else 0

    # --------------------------------------------------------- telemetry
    def start_metrics_server(self, port=0, host="127.0.0.1"):
        """Expose /metrics (Prometheus), /metrics.json and /healthz on a
        stdlib-http.server background thread. port=0 picks a free port
        (read it back from the returned server's .port). Idempotent for
        matching args; asking for a DIFFERENT host/port while a server
        is live raises instead of silently keeping the old address."""
        if self._metrics_server is not None:
            srv = self._metrics_server
            if host != srv.host or port not in (0, srv.port):
                raise RuntimeError(
                    f"metrics server already running at {srv.url}; call "
                    "stop_metrics_server() before rebinding to "
                    f"{host}:{port}")
            return srv
        self._metrics_server = telemetry.MetricsServer(
            host=host, port=port, health_fn=self._health).start()
        return self._metrics_server

    def stop_metrics_server(self):
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None

    def attach_queue_probe(self, fn):
        """Register a zero-arg queue-depth callable (the Scheduler's) —
        folded into /healthz so load balancers and the fleet router get
        queue state without a /metrics scrape. The newest scheduler
        wins (benches build a fresh Scheduler per load point over one
        engine)."""
        self._queue_depth_fn = fn

    def attach_health_probe(self, fn):
        """Register a zero-arg dict-returning callable merged into the
        /healthz payload — the scheduler's SLO engine serves its
        burn-rate verdict through this. Newest wins, same contract as
        the queue probe."""
        self._health_probe_fn = fn

    def set_slot_trace(self, slot, trace_id, trace_pid=0):
        """Park the admitted request's trace context on its slot so
        engine-internal progress events (chunked prefill) can correlate
        to the request's chrome flow. Cleared at retirement."""
        self._slot_trace[slot] = (int(trace_id), int(trace_pid))

    def program_costs(self):
        """FLOPs / bytes-accessed per invocation of this engine's two
        compiled programs, from the xprof registry's specs at THIS
        engine's real shapes (lowering-level HLO cost analysis — no
        second backend compile; the same numbers
        scripts/hlo_baseline.json banks for the canonical shapes).
        Returns {"decode_wave": {...}|None, "prefill": {...}|None};
        memoized per engine AND per shape signature process-wide, so a
        fleet of identical replicas lowers once. {} when the audit
        registry cannot analyze on this jax build."""
        if self._program_costs_memo is not None:
            return self._program_costs_memo
        # caches are part of the key: they carry the pool/cache dims
        # (block_size, num_blocks, cache dtype) that change the
        # program's bytes-accessed even over identical weights
        sig = (type(self).__name__, self.num_slots, self.max_len,
               self.prefill_len,
               tuple((tuple(leaf.shape), str(leaf.dtype))
                     for leaf in jax.tree_util.tree_leaves(
                         (self._params, self._buffers, self._caches))))
        costs = _PROGRAM_COST_CACHE.get(sig)
        if costs is None:
            from ..tools.xprof.registry import (engine_program_specs,
                                                program_cost)
            costs = {}
            try:
                for spec in engine_program_specs(self):
                    name = spec["name"]
                    key = ("prefill" if "prefill" in name
                           else "draft_wave" if "draft" in name
                           else "verify" if "verify" in name
                           else "decode_wave")
                    costs[key] = program_cost(spec)
            except Exception:   # noqa: BLE001 — cost analysis is
                costs = {}      # best-effort observability, never a
                                # reason to fail serving
            _PROGRAM_COST_CACHE[sig] = costs
        self._program_costs_memo = costs
        return costs

    def set_health_state(self, state):
        """ok | degraded | draining — the scheduler flips this so
        /healthz reports REAL engine state (a degraded engine must not
        answer "ok" to a load balancer)."""
        if state not in HEALTH_STATES:
            raise ValueError(f"health state must be one of "
                             f"{HEALTH_STATES}, got {state!r}")
        self.health_state = state

    def _health(self):
        qfn = self._queue_depth_fn
        h = {
            "status": self.health_state,
            "num_slots": self.num_slots,
            "slots_active": len(self.active_slots()),
            "queue_depth": int(qfn()) if qfn is not None else 0,
            "max_len": self.max_len,
            "decode_compiles": self.decode_compiles,
            "prefill_compiles": self.prefill_compiles,
        }
        if self._health_probe_fn is not None:
            # e.g. {"slo": {...burn-rate verdict...}} — the handler
            # already degrades the payload if a probe raises
            h.update(self._health_probe_fn() or {})
        return h

    # ------------------------------------------------------------- slots
    def free_slots(self):
        return [i for i, a in enumerate(self.slot_active)
                if not a and i not in self._pending_prefill]

    def active_slots(self):
        return [i for i, a in enumerate(self.slot_active) if a]

    def prefilling_slots(self):
        """Slots admitted but still mid-prefill (paged chunked prefill;
        at most one scheduling round for the dense engine)."""
        return sorted(self._pending_prefill)

    def describe(self):
        """Replay-relevant construction config. The black-box journal
        records this in `run_start` harness metadata so
        scripts/replay_incident.py can rebuild an identical engine
        (same seed => same PRNG chain => sampled streams replay
        token-exact)."""
        return {"engine": "dense", "num_slots": self.num_slots,
                "max_len": self.max_len, "prefill_len": self.prefill_len,
                "seed": self.seed,
                "cache_dtype": np.dtype(self.cache_dtype).name}

    def validate_prompt(self, prompt):
        """Admission check: the prompt must fit the prefill bucket and
        leave room to decode at least one token under the cache horizon."""
        n = len(prompt)
        if n > self.prefill_len:
            return (f"prompt length {n} exceeds the prefill bucket "
                    f"{self.prefill_len} (engine prefill_len)")
        if n + 1 > self.max_len:
            return (f"prompt length {n} leaves no room to decode under "
                    f"max_len {self.max_len}")
        return None

    def _normalize_bias(self, logit_bias):
        """One [V] float32 bias row from the request surface: None,
        a {token_id: bias} dict, or a [V] array-like (a boolean array is
        read as an ALLOWED mask: True = untouched, False = -1e9)."""
        row = np.zeros((self.vocab_size,), np.float32)
        if logit_bias is None:
            return row
        if isinstance(logit_bias, dict):
            for t, v in logit_bias.items():
                row[int(t)] = float(v)
            return row
        arr = np.asarray(logit_bias)
        if arr.shape != (self.vocab_size,):
            raise ValueError(
                f"logit bias/mask must be [{self.vocab_size}] "
                f"(vocab), got {arr.shape}")
        if arr.dtype == bool:
            return np.where(arr, 0.0, -1e9).astype(np.float32)
        return arr.astype(np.float32)

    def set_slot_bias(self, slot, bias, dynamic=True):
        """Replace the slot's logit-bias/token-mask row mid-stream — the
        scheduler's per-wave token_mask refresh (constrained decoding:
        the allowed set changes as tokens land). `dynamic` keeps the
        lane flagged so a speculative engine won't draft ahead of it."""
        self._set_bias_row(slot, self._normalize_bias(bias))
        self.slot_dynamic_mask[slot] = bool(dynamic)

    def _set_bias_row(self, slot, row):
        """Write one slot's bias row, invalidating the device copy only
        when the row's content actually changes zero-ness — a stream of
        bias-free requests uploads the [S, V] matrix exactly once."""
        nonzero = bool(np.any(row))
        if nonzero or self._slot_bias_nonzero[slot]:
            self._slot_bias_dev = None
        self._slot_bias[slot] = row
        self._slot_bias_nonzero[slot] = nonzero

    def _arm_slot(self, slot, first, n, sampling):
        """Post-prefill slot arming shared by the dense and paged
        admission paths: the request's whole sampling surface becomes
        per-slot vectors for the next wave."""
        self.slot_active[slot] = True
        self.slot_pos[slot] = n
        self.slot_tok[slot] = first
        self.slot_sample[slot] = bool(sampling["sample"])
        self.slot_temp[slot] = float(sampling["temp"])
        self.slot_top_k[slot] = int(sampling["top_k"])
        self.slot_top_p[slot] = float(sampling["top_p"])
        self._set_bias_row(slot, sampling["bias"])
        self.slot_dynamic_mask[slot] = bool(sampling["dynamic_mask"])

    def _sampling_state(self, do_sample, temperature, top_k, top_p,
                        logit_bias, dynamic_mask):
        return {"sample": bool(do_sample), "temp": float(temperature),
                "top_k": int(top_k), "top_p": float(top_p),
                "bias": self._normalize_bias(logit_bias),
                "dynamic_mask": bool(dynamic_mask)}

    def begin_prefill(self, slot, prompt, do_sample=False,
                      temperature=1.0, top_k=0, top_p=1.0,
                      logit_bias=None, dynamic_mask=False):
        """Stage an admission: validate and park the prompt on the slot.
        The work itself runs in prefill_step — the scheduler's advance
        phase — so engines whose prefill spans several rounds (paged
        chunked prefill) keep decode waves flowing while a long prompt
        is mid-admission. The dense engine completes in ONE
        prefill_step."""
        why = self.validate_prompt(prompt)
        if why:
            raise ValueError(why)
        if self.slot_active[slot] or slot in self._pending_prefill:
            raise RuntimeError(f"slot {slot} is busy")
        self._pending_prefill[slot] = (
            list(prompt),
            self._sampling_state(do_sample, temperature, top_k, top_p,
                                 logit_bias, dynamic_mask))

    def prefill_step(self, slot):
        """Advance the slot's admission one step. Returns the request's
        FIRST generated token (host int) when the prefill completed,
        None while more steps remain (the dense bucket prefill always
        completes here). Routed through prefill_slot so engine users
        (and test seams) that override it see every admission."""
        prompt, sampling = self._pending_prefill.pop(slot)
        return self.prefill_slot(
            slot, prompt, do_sample=sampling["sample"],
            temperature=sampling["temp"], top_k=sampling["top_k"],
            top_p=sampling["top_p"], logit_bias=sampling["bias"],
            dynamic_mask=sampling["dynamic_mask"])

    def prefill_slot(self, slot, prompt, do_sample=False, temperature=1.0,
                     top_k=0, top_p=1.0, logit_bias=None,
                     dynamic_mask=False):
        """Admit a prompt into a free slot: run the prefill program,
        splice the slot's cache region, arm the slot for the next wave.
        Returns the request's FIRST generated token (host int)."""
        why = self.validate_prompt(prompt)
        if why:
            raise ValueError(why)
        return self._prefill_slot_armed(
            slot, list(prompt),
            self._sampling_state(do_sample, temperature, top_k, top_p,
                                 logit_bias, dynamic_mask))

    def _prefill_slot_armed(self, slot, prompt, sampling):
        if self.slot_active[slot]:
            raise RuntimeError(f"slot {slot} is busy")
        if chaos.enabled():
            # host-side, before any state mutates or the donated cache
            # reaches the program — a fired fault leaves the engine
            # exactly as it was, so the scheduler can fail JUST this
            # request and keep serving
            chaos.fire(chaos.PREFILL, slot=slot)
        n = len(prompt)
        padded = np.zeros((self.prefill_len,), np.int32)
        padded[:n] = np.asarray(prompt, np.int32)
        self._key, sub = jax.random.split(self._key)
        first, self._caches = self._prefill(
            self._params, self._buffers, self._caches,
            jnp.asarray(padded), jnp.int32(n), jnp.int32(slot),
            jnp.asarray(sampling["sample"]),
            jnp.float32(sampling["temp"]),
            jnp.int32(sampling["top_k"]), jnp.float32(sampling["top_p"]),
            jnp.asarray(sampling["bias"]), sub)
        first = int(np.asarray(first))
        self._arm_slot(slot, first, n, sampling)
        return first

    def decode_wave(self):
        """One batched decode step over all slots. Returns {slot: token}
        for the slots that were active this wave AND produced finite
        logits; slots whose logits went non-finite are excluded, frozen
        in-program, and listed in `last_nonfinite_slots` for the
        scheduler to retire (finish_reason "error"). Inactive lanes
        ride along frozen.

        Raise-type faults (chaos, or a real host-side error) fire
        BEFORE the key splits or the donated cache reaches the program,
        so a failed wave mutates nothing and a retry replays exactly.
        An error from inside the compiled call itself may have consumed
        the donated cache — the retry then fails too and the scheduler
        degrades gracefully instead of looping."""
        active_now = list(self.slot_active)
        if not any(active_now):
            self.last_nonfinite_slots = []
            self.last_starved_slots = []
            return {}
        if chaos.enabled():
            chaos.fire(chaos.DECODE_WAVE, active=sum(active_now))
        # back each lane's next cache write (paged engines allocate
        # blocks here; a starved lane is excluded from this wave and
        # reported in last_starved_slots for the scheduler to preempt).
        # Idempotent, so a retried wave replays exactly.
        active_now = self._prepare_wave(active_now)
        if not any(active_now):
            self.last_nonfinite_slots = []
            return {}
        poison = np.zeros((self.num_slots,), bool)
        if chaos.enabled():
            hit = chaos.value(chaos.DECODE_WAVE_NAN)
            if hit is not None:
                for s in np.atleast_1d(hit):
                    poison[int(s)] = True
        self._key, sub = jax.random.split(self._key)
        tok, pos, finite, self._caches = self._decode_wave(
            *self._wave_args(active_now, poison, sub))
        tok = np.asarray(tok)
        finite = np.asarray(finite)
        out, bad = {}, []
        for s, was_active in enumerate(active_now):
            if not was_active:
                continue
            if not bool(finite[s]):
                bad.append(s)       # lane frozen in-program; caller
                continue            # must retire it before the next wave
            self.slot_pos[s] += 1
            self.slot_tok[s] = int(tok[s])
            out[s] = int(tok[s])
        self.last_nonfinite_slots = bad
        return out

    def _prepare_wave(self, active_now):
        """Hook: ensure each active lane's next cache write has backing
        storage. Dense rows always do; the paged engine allocates blocks
        on demand and drops starved lanes from the wave."""
        self.last_starved_slots = []
        return active_now

    def _sampling_args(self):
        """The sampling-scenario vectors every wave uploads (per-slot
        knobs + the [S, V] bias/mask matrix) — one place, so the dense,
        paged and speculative wave argument tuples cannot drift."""
        if self._slot_bias_dev is None:
            self._slot_bias_dev = jnp.asarray(self._slot_bias)
        return (jnp.asarray(self.slot_sample, bool),
                jnp.asarray(self.slot_temp, jnp.float32),
                jnp.asarray(self.slot_top_k, jnp.int32),
                jnp.asarray(self.slot_top_p, jnp.float32),
                self._slot_bias_dev)

    def _wave_args(self, active_now, poison, key):
        """The decode-wave program's argument tuple (the paged engine
        inserts its block tables after the donated caches)."""
        return (self._params, self._buffers, self._caches,
                jnp.asarray(self.slot_tok, jnp.int32),
                jnp.asarray(self.slot_pos, jnp.int32),
                jnp.asarray(active_now, bool),
                *self._sampling_args(),
                jnp.asarray(poison), key)

    def slot_full(self, slot):
        """True when the slot's next write would fall past the cache
        horizon (max_len - 1 is the last legal write) — the scheduler
        must retire it (finish_reason 'length') before the next wave."""
        return self.slot_pos[slot] >= self.max_len

    def retire_slot(self, slot):
        """Free a slot between waves. The cache region is left as-is:
        the next prefill overwrites [0, P) and the decode frontier
        rewrites every position before the ks<=pos mask exposes it.
        Also aborts a mid-prefill admission parked on the slot."""
        self.slot_active[slot] = False
        self.slot_sample[slot] = False
        self.slot_temp[slot] = 1.0
        self.slot_top_k[slot] = 0
        self.slot_top_p[slot] = 1.0
        self.slot_dynamic_mask[slot] = False
        self._set_bias_row(slot, np.zeros((self.vocab_size,), np.float32))
        self._pending_prefill.pop(slot, None)
        self._slot_trace.pop(slot, None)
