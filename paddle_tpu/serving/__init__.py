"""paddle_tpu.serving — slot-based continuous-batching LLM serving.

The reference ships a ~38K-LoC inference engine (AnalysisPredictor +
config); its TPU-native replacement is a request SCHEDULER over the
XLA-compiled decode step: a fixed [num_slots, max_len] batched KV cache,
ONE compiled batched decode program reused across the whole request
stream (per-slot position vector + active mask + where-based
retirement), and mid-stream prefill into free slots. See
docs/serving.md for the architecture.

    from paddle_tpu import serving
    engine = serving.ServingEngine(model, num_slots=4, max_len=256)
    sched = serving.Scheduler(engine)
    req = sched.submit(prompt=[1, 2, 3], max_tokens=32,
                       on_token=lambda r, t: print(t))
    sched.run()                    # drains queue + slots

Observability (docs/observability.md): requests carry trace ids and
emit chrome-trace spans/flows through utils.telemetry; serving counters
and TTFT/latency histograms live in the typed metric registry; and
`engine.start_metrics_server()` (or
inference.Config.enable_metrics_exporter) serves /metrics + /healthz.

Resilience (docs/robustness.md): per-request fault isolation
(a failed prefill or non-finite decode lane resolves only ITS request
with finish_reason "error"), wave retry with bounded exponential
backoff then graceful degradation, bounded-queue load shedding +
`Scheduler.drain()`, and real /healthz state (ok/degraded/draining) —
every path proven by deterministic injection (utils.chaos,
scripts/chaos_serving.py).
"""
from .engine import ServingEngine
from .scheduler import Scheduler
from .request import Request, RequestState
from .metrics import ServingMetrics
from .slo import SLOEngine, SLOPolicy
from .paged import (BlockPool, BlockPoolExhausted, HandoffRefused,
                    PagedServingEngine, SpeculativePagedEngine)
from .fleet import (DisaggFleetRouter, FleetRequest, FleetRouter,
                    QoSManager, Tenant)

__all__ = ["ServingEngine", "Scheduler", "Request", "RequestState",
           "ServingMetrics", "SLOEngine", "SLOPolicy",
           "BlockPool", "BlockPoolExhausted", "HandoffRefused",
           "PagedServingEngine", "SpeculativePagedEngine",
           "FleetRouter", "FleetRequest", "DisaggFleetRouter",
           "QoSManager", "Tenant"]
