"""Serving metrics: TTFT distribution, token throughput, queue depth and
slot occupancy.

Two sinks, one recording path:

  * the typed telemetry registry (utils/telemetry.py) — labeled
    counters/gauges plus BOUNDED exponential-bucket histograms for
    TTFT/latency, rendered on /metrics and in JSON snapshots. The
    histograms replaced the raw per-request sample lists, so a
    long-running engine's memory no longer grows with request count;
    p50/p99 come from bucket interpolation.
  * the legacy flat `utils.monitor` stat registry (`serving_*` keys),
    kept so `monitor.all_stats()` callers see the same counters.

`ServingMetrics.snapshot()` keys are byte-compatible with the PR-1
shape (`scripts/bench_serving.py` serializes it unchanged).
"""
import threading

from ..utils import flight_recorder, monitor, telemetry

#: scheduler-round phases whose wall time is attributed per round —
#: admission (queue pop + block alloc + staging), prefill_chunk (one
#: prefill program per mid-admission slot), decode_wave (the batched
#: wave INCLUDING its fused in-program sampling tail), host_dispatch
#: (token emit + callbacks + retirement). Keys of snapshot()'s
#: `phase_seconds`.
PHASES = ("admission", "prefill_chunk", "decode_wave", "host_dispatch")

# legacy stat-registry keys (monitor.stat_get / all_stats)
REQUESTS_SUBMITTED = "serving_requests_submitted"
REQUESTS_COMPLETED = "serving_requests_completed"
REQUESTS_REJECTED = "serving_requests_rejected"
TOKENS_GENERATED = "serving_tokens_generated"
PREFILLS = "serving_prefills"
DECODE_WAVES = "serving_decode_waves"
QUEUE_DEPTH_PEAK = "serving_queue_depth_peak"
# NOTE: `serving_queue_depth` / `serving_slots_active` are TYPED gauges
# only — the monitor keys of the same name used to ride along in every
# exposition just to be shadowed by the typed series (the documented
# legacy-monitor wart); ServingMetrics.snapshot() keys are unchanged.

# typed registry metrics (docs/observability.md catalogs these)
_REQUESTS = telemetry.counter(
    "serving_requests_total", "Requests by lifecycle event",
    labelnames=("state",))
_TOKENS = telemetry.counter(
    "serving_tokens_generated_total", "Generated tokens streamed to hosts")
_PREFILLS = telemetry.counter(
    "serving_prefills_total", "Prefill program invocations (admissions)")
_WAVES = telemetry.counter(
    "serving_decode_waves_total", "Batched decode waves executed")
_QUEUE_DEPTH = telemetry.gauge(
    "serving_queue_depth", "Requests waiting for a slot")
_SLOTS_ACTIVE = telemetry.gauge(
    "serving_slots_active", "Slots decoding in the latest wave")
_TTFT = telemetry.histogram(
    "serving_ttft_seconds", "Time from submit to first token",
    buckets=telemetry.DEFAULT_LATENCY_BUCKETS)
_LATENCY = telemetry.histogram(
    "serving_request_latency_seconds", "Time from submit to completion",
    buckets=telemetry.DEFAULT_LATENCY_BUCKETS)
# inter-token latency needs finer buckets than TTFT: a healthy decode
# wave is sub-millisecond-to-tens-of-ms, right at the default latency
# buckets' floor (these span 100us..~3.3s)
TPOT_BUCKETS = telemetry.exponential_buckets(0.0001, 2.0, 16)
_TPOT = telemetry.histogram(
    "serving_tpot_seconds",
    "Inter-token latency (gap between consecutive streamed tokens of "
    "one request; the first token's latency is TTFT, not TPOT)",
    buckets=TPOT_BUCKETS)
# serving roofline: the decode wave is memory-bandwidth-bound, so BOTH
# axes are exported — compute (MFU) and HBM-bandwidth utilization —
# from the compiled program's own cost analysis (the same flops/bytes
# scripts/hlo_baseline.json banks) over the measured wave time
_MFU = telemetry.gauge(
    "serving_mfu",
    "Model-FLOPs utilization of the latest decode wave: program FLOPs "
    "/ (wave seconds x device peak FLOP/s)")
_HBM_UTIL = telemetry.gauge(
    "serving_hbm_util",
    "HBM-bandwidth utilization of the latest decode wave: program "
    "bytes-accessed / (wave seconds x device peak HBM bandwidth) — the "
    "roofline axis that actually binds decode")

_DEVICE_PEAKS = []     # [(peak_flops, peak_hbm_bw)] resolved once


def _device_peaks():
    """The roofline denominators, resolved once per process — they are
    device constants, and on_wave sits in the hottest serving loop
    (sub-millisecond waves), where two env + JAX-client lookups per
    wave are real overhead."""
    if not _DEVICE_PEAKS:
        _DEVICE_PEAKS.append((flight_recorder.device_peak_flops(),
                              flight_recorder.device_peak_hbm_bw()))
    return _DEVICE_PEAKS[0]
# resilience counters (the chaos harness proves each one moves —
# scripts/chaos_serving.py; kinds are a small closed set)
_FAULTS = telemetry.counter(
    "serving_faults_total",
    "Faults handled by the resilience layer (isolated, retried, or "
    "degraded — never a stack trace to the caller)",
    labelnames=("kind",))
_REJECTED = telemetry.counter(
    "serving_rejected_total",
    "Requests shed at admission: queue full, draining, degraded, or "
    "invalid prompt")
_WAVE_RETRIES = telemetry.counter(
    "serving_wave_retries_total",
    "Decode-wave retry attempts after a transient wave failure")
_CALLBACK_ERRORS = telemetry.counter(
    "serving_callback_errors_total",
    "Exceptions raised by client on_token callbacks (contained "
    "per-request, never poisoning the shared wave loop)")
# paged KV cache (serving/paged): pool pressure + prefix-cache efficacy
_CACHE_BLOCKS_USED = telemetry.gauge(
    "serving_cache_blocks_used",
    "KV-cache blocks currently referenced by live requests (paged "
    "engine block pool)")
_CACHE_BLOCKS_TOTAL = telemetry.gauge(
    "serving_cache_blocks_total",
    "Usable KV-cache blocks in the paged engine's pool (scratch "
    "excluded) — used/total is the utilization that replaces dense "
    "slot occupancy")
_PREFIX_HITS = telemetry.counter(
    "serving_prefix_cache_hits_total",
    "Full prompt blocks served from the hash-based prefix cache "
    "(shared system prompts dedupe onto the same physical blocks)")
_PREFIX_MISSES = telemetry.counter(
    "serving_prefix_cache_misses_total",
    "Full prompt blocks that had to be computed by prefill (no cached "
    "block with a matching chain hash)")
# speculative decoding (serving/paged SpeculativePagedEngine): the
# draft-k/verify-once wave's economics — acceptance rate IS the
# speedup knob (mean accepted/wave > 0 means decode rounds per
# generated token dropped below 1:1)
_SPEC_PROPOSED = telemetry.counter(
    "serving_spec_tokens_proposed_total",
    "Draft tokens proposed to the verify wave (speculative decoding; "
    "per-lane spec_len after horizon/token-mask clamps)")
_SPEC_ACCEPTED = telemetry.counter(
    "serving_spec_tokens_accepted_total",
    "Draft tokens accepted by the exact acceptance-rejection tail "
    "(the bonus/correction token per lane is not a draft's and is "
    "never counted here)")
_SPEC_RATE = telemetry.gauge(
    "serving_spec_acceptance_rate",
    "Cumulative accepted/proposed ratio of the speculative decode "
    "path (draft-model quality at the currently served traffic)")


def record_block_usage(used, total):
    """Export the paged pool's occupancy (called by BlockPool on every
    alloc/release)."""
    _CACHE_BLOCKS_USED.set(int(used))
    _CACHE_BLOCKS_TOTAL.set(int(total))


def record_prefix_lookup(hits, misses):
    """Count one admission's prefix-cache outcome, block-granular."""
    if hits:
        _PREFIX_HITS.inc(int(hits))
    if misses:
        _PREFIX_MISSES.inc(int(misses))


def record_callback_error(request, error):
    """Count + journal a contained client-callback exception (called
    from Request._emit — client bugs stay visible without breaking the
    per-request isolation that swallows them)."""
    _CALLBACK_ERRORS.inc()
    rec = flight_recorder.get_recorder()
    if rec is not None:
        rec.fault(kind="callback_error", action="contained",
                  request_id=request.request_id, error=repr(error))


class ServingMetrics:
    """Per-engine aggregation on top of the process-wide sinks: bounded
    TTFT/latency histograms (for this instance's p50/p99) and the
    occupancy integral (active-slot-waves / total-slot-waves)."""

    def __init__(self, num_slots):
        self.num_slots = num_slots
        self._lock = threading.Lock()
        # instance-local (unregistered) histograms: a fresh Scheduler
        # gets fresh percentiles while the registered process-wide
        # histograms keep accumulating for /metrics
        self._ttft = telemetry.Histogram(
            "serving_ttft_seconds", buckets=telemetry.DEFAULT_LATENCY_BUCKETS)
        self._latency = telemetry.Histogram(
            "serving_request_latency_seconds",
            buckets=telemetry.DEFAULT_LATENCY_BUCKETS)
        self._tpot = telemetry.Histogram(
            "serving_tpot_seconds", buckets=TPOT_BUCKETS)
        self._active_slot_waves = 0
        self._total_slot_waves = 0
        self._tokens = 0
        self._queue_peak = 0
        self._first_token_time = None
        self._last_token_time = None
        self._faults = {}
        self._rejected = 0
        self._wave_retries = 0
        # paged-pool tracking (None until a paged engine reports):
        # utilization is the block-wave integral — the paged analog of
        # slot occupancy — and the prefix tallies are deltas of the
        # pool's monotonic counters over THIS instance's lifetime
        self._block_used_waves = 0
        self._block_total_waves = 0
        self._prefix_base = None
        self._prefix_last = None
        # per-phase wall time (seconds, accumulated per scheduler
        # round) and the wave-integral roofline numerators: program
        # flops/bytes x waves over the summed wave seconds
        self._phase_seconds = {}
        self._wave_seconds = 0.0
        self._wave_flops = 0.0
        self._wave_bytes = 0.0
        # speculative decoding tallies (0 on non-speculative engines)
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_waves = 0

    # ---------------------------------------------------------- recording
    def on_submit(self):
        monitor.stat_add(REQUESTS_SUBMITTED)
        _REQUESTS.labels(state="submitted").inc()

    def on_reject(self):
        monitor.stat_add(REQUESTS_REJECTED)
        _REQUESTS.labels(state="rejected").inc()
        _REJECTED.inc()
        with self._lock:
            self._rejected += 1

    def on_fault(self, kind):
        _FAULTS.labels(kind=kind).inc()
        with self._lock:
            self._faults[kind] = self._faults.get(kind, 0) + 1

    def on_wave_retry(self):
        _WAVE_RETRIES.inc()
        with self._lock:
            self._wave_retries += 1

    def on_prefill(self):
        monitor.stat_add(PREFILLS)
        _PREFILLS.inc()

    def on_wave(self, n_active, wave_s=None, flops=None,
                bytes_accessed=None):
        """One dispatched decode wave. `wave_s` is the measured wave
        wall time and flops/bytes_accessed the compiled program's cost
        per invocation (engine.program_costs — the numbers the xprof
        baseline banks); together they produce the serving roofline
        gauges. Cost-less calls (analysis unavailable) still count the
        wave."""
        monitor.stat_add(DECODE_WAVES)
        _WAVES.inc()
        _SLOTS_ACTIVE.set(int(n_active))
        with self._lock:
            self._active_slot_waves += int(n_active)
            self._total_slot_waves += self.num_slots
            if wave_s is not None and wave_s > 0:
                self._wave_seconds += float(wave_s)
                self._wave_flops += float(flops or 0.0)
                self._wave_bytes += float(bytes_accessed or 0.0)
        if wave_s is not None and wave_s > 0:
            peak_flops, peak_bw = _device_peaks()
            if flops:
                _MFU.set(float(flops) / (wave_s * peak_flops))
            if bytes_accessed:
                _HBM_UTIL.set(float(bytes_accessed) / (wave_s * peak_bw))

    def on_spec(self, proposed, accepted):
        """One speculative wave's draft economics (scheduler-reported:
        proposed = sum of per-lane spec_len, accepted = draft tokens the
        acceptance kept). Updates the process-wide counters and the
        cumulative acceptance-rate gauge."""
        if proposed:
            _SPEC_PROPOSED.inc(int(proposed))
        if accepted:
            _SPEC_ACCEPTED.inc(int(accepted))
        with self._lock:
            self._spec_proposed += int(proposed)
            self._spec_accepted += int(accepted)
            self._spec_waves += 1
            if self._spec_proposed:
                _SPEC_RATE.set(self._spec_accepted / self._spec_proposed)

    def on_phase(self, phase, seconds):
        """Attribute one scheduler-round phase's wall time (keys in
        `PHASES`; snapshot() reports the accumulated split)."""
        if seconds is None:
            return
        with self._lock:
            self._phase_seconds[phase] = (
                self._phase_seconds.get(phase, 0.0) + float(seconds))

    def on_queue_depth(self, depth):
        monitor.stat_max(QUEUE_DEPTH_PEAK, int(depth))  # process-wide peak
        _QUEUE_DEPTH.set(int(depth))
        with self._lock:
            self._queue_peak = max(self._queue_peak, int(depth))

    def on_blocks(self, used, total):
        """One scheduling round's paged-pool occupancy sample."""
        with self._lock:
            self._block_used_waves += int(used)
            self._block_total_waves += int(total)

    def on_prefix_totals(self, hits, misses):
        """Track the pool's monotonic prefix counters; snapshot reports
        the delta across this metrics instance (per-load-point rates in
        the bench, which builds a fresh Scheduler per point)."""
        with self._lock:
            if self._prefix_base is None:
                self._prefix_base = (int(hits), int(misses))
            self._prefix_last = (int(hits), int(misses))

    def on_token(self, t_now, prev_t=None):
        """One streamed token; `prev_t` is the SAME request's previous
        token timestamp (None for its first token), so the gap is a
        TPOT sample — per-request inter-token latency, not the
        engine-wide token cadence."""
        monitor.stat_add(TOKENS_GENERATED)
        _TOKENS.inc()
        if prev_t is not None:
            gap = t_now - prev_t
            self._tpot.observe(gap)
            _TPOT.observe(gap)
        with self._lock:
            self._tokens += 1
            if self._first_token_time is None:
                self._first_token_time = t_now
            self._last_token_time = t_now

    def on_complete(self, request):
        monitor.stat_add(REQUESTS_COMPLETED)
        _REQUESTS.labels(state="completed").inc()
        if request.ttft is not None:
            self._ttft.observe(request.ttft)
            _TTFT.observe(request.ttft)
        if request.latency is not None:
            self._latency.observe(request.latency)
            _LATENCY.observe(request.latency)

    # ---------------------------------------------------------- reporting
    def snapshot(self):
        """Point-in-time summary dict (the bench script serializes this).
        Keys are byte-compatible with the raw-sample-list era; the
        percentiles are now bucket-interpolated estimates."""
        with self._lock:
            active, total = self._active_slot_waves, self._total_slot_waves
            tokens = self._tokens
            first_t, last_t = (self._first_token_time,
                               self._last_token_time)
            span = (None if first_t is None or last_t is None
                    else last_t - first_t)
            queue_peak = self._queue_peak
            faults = dict(self._faults)
            rejected, wave_retries = self._rejected, self._wave_retries
            blk_used, blk_total = (self._block_used_waves,
                                   self._block_total_waves)
            if self._prefix_base is None:
                p_hits = p_misses = 0
            else:
                p_hits = self._prefix_last[0] - self._prefix_base[0]
                p_misses = self._prefix_last[1] - self._prefix_base[1]
            phase_seconds = dict(self._phase_seconds)
            wave_s = self._wave_seconds
            wave_flops, wave_bytes = self._wave_flops, self._wave_bytes
            spec_p, spec_a = self._spec_proposed, self._spec_accepted
            spec_w = self._spec_waves
        return {
            "requests_completed": self._latency.count(),
            "tokens_generated": tokens,
            "tokens_per_s": (tokens / span if span else None),
            "ttft_p50_s": self._ttft.percentile(50),
            "ttft_p99_s": self._ttft.percentile(99),
            "latency_p50_s": self._latency.percentile(50),
            "latency_p99_s": self._latency.percentile(99),
            "slot_occupancy": (active / total if total else 0.0),
            "queue_depth_peak": queue_peak,   # this instance, not the
                                              # process-wide monitor stat
            # resilience tallies (this instance): shedding onset vs
            # offered load shows up in bench rows through these
            "faults": faults,
            "rejected": rejected,
            "wave_retries": wave_retries,
            # paged KV pool (None/0 on a dense engine): utilization is
            # the block-wave integral — HBM held by ACTUAL tokens, the
            # number that replaces dense slot occupancy
            "block_utilization": (blk_used / blk_total if blk_total
                                  else None),
            "prefix_hits": p_hits,
            "prefix_misses": p_misses,
            "prefix_hit_rate": (p_hits / (p_hits + p_misses)
                                if p_hits + p_misses else None),
            # fleet PR: raw span endpoints (monotonic clock), so a
            # multi-replica rollup can compute the FLEET's first-to-
            # last-token span (max(last) - min(first)) and keep its
            # tokens/s denominator comparable with single-engine rows
            "first_token_time": first_t,
            "last_token_time": last_t,
            # observability PR: inter-token latency (the second half of
            # the TTFT/TPOT request-latency decomposition), the per-
            # round phase split, and the wave-integral roofline —
            # flops/bytes per wave are the SAME numbers the xprof
            # baseline banks, so these agree with hlo_baseline.json
            "tpot_p50_s": self._tpot.percentile(50),
            "tpot_p99_s": self._tpot.percentile(99),
            "phase_seconds": phase_seconds,
            "mfu": (wave_flops / (wave_s * _device_peaks()[0])
                    if wave_s and wave_flops else None),
            "hbm_util": (wave_bytes / (wave_s * _device_peaks()[1])
                         if wave_s and wave_bytes else None),
            # speculative decoding (perf PR): 0/None on engines without
            # a draft model. accepted_per_wave is the headline number —
            # > 0 means each wave nets more than one token per lane
            "spec_tokens_proposed": spec_p,
            "spec_tokens_accepted": spec_a,
            "spec_acceptance_rate": (spec_a / spec_p if spec_p
                                     else None),
            "spec_accepted_per_wave": (spec_a / spec_w if spec_w
                                       else None),
        }
