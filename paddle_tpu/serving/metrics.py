"""Serving metrics: TTFT distribution, token throughput, queue depth and
slot occupancy.

Two sinks, one recording path:

  * the typed telemetry registry (utils/telemetry.py) — labeled
    counters/gauges plus BOUNDED exponential-bucket histograms for
    TTFT/latency, rendered on /metrics and in JSON snapshots. The
    histograms replaced the raw per-request sample lists, so a
    long-running engine's memory no longer grows with request count;
    p50/p99 come from bucket interpolation.
  * the legacy flat `utils.monitor` stat registry (`serving_*` keys),
    kept so `monitor.all_stats()` callers see the same counters.

`ServingMetrics.snapshot()` keys are byte-compatible with the PR-1
shape (`scripts/bench_serving.py` serializes it unchanged).
"""
import threading

from ..utils import flight_recorder, monitor, telemetry

# legacy stat-registry keys (monitor.stat_get / all_stats)
REQUESTS_SUBMITTED = "serving_requests_submitted"
REQUESTS_COMPLETED = "serving_requests_completed"
REQUESTS_REJECTED = "serving_requests_rejected"
TOKENS_GENERATED = "serving_tokens_generated"
PREFILLS = "serving_prefills"
DECODE_WAVES = "serving_decode_waves"
QUEUE_DEPTH = "serving_queue_depth"
SLOTS_ACTIVE = "serving_slots_active"
QUEUE_DEPTH_PEAK = "serving_queue_depth_peak"

# typed registry metrics (docs/observability.md catalogs these)
_REQUESTS = telemetry.counter(
    "serving_requests_total", "Requests by lifecycle event",
    labelnames=("state",))
_TOKENS = telemetry.counter(
    "serving_tokens_generated_total", "Generated tokens streamed to hosts")
_PREFILLS = telemetry.counter(
    "serving_prefills_total", "Prefill program invocations (admissions)")
_WAVES = telemetry.counter(
    "serving_decode_waves_total", "Batched decode waves executed")
_QUEUE_DEPTH = telemetry.gauge(
    "serving_queue_depth", "Requests waiting for a slot")
_SLOTS_ACTIVE = telemetry.gauge(
    "serving_slots_active", "Slots decoding in the latest wave")
_TTFT = telemetry.histogram(
    "serving_ttft_seconds", "Time from submit to first token",
    buckets=telemetry.DEFAULT_LATENCY_BUCKETS)
_LATENCY = telemetry.histogram(
    "serving_request_latency_seconds", "Time from submit to completion",
    buckets=telemetry.DEFAULT_LATENCY_BUCKETS)
# resilience counters (the chaos harness proves each one moves —
# scripts/chaos_serving.py; kinds are a small closed set)
_FAULTS = telemetry.counter(
    "serving_faults_total",
    "Faults handled by the resilience layer (isolated, retried, or "
    "degraded — never a stack trace to the caller)",
    labelnames=("kind",))
_REJECTED = telemetry.counter(
    "serving_rejected_total",
    "Requests shed at admission: queue full, draining, degraded, or "
    "invalid prompt")
_WAVE_RETRIES = telemetry.counter(
    "serving_wave_retries_total",
    "Decode-wave retry attempts after a transient wave failure")
_CALLBACK_ERRORS = telemetry.counter(
    "serving_callback_errors_total",
    "Exceptions raised by client on_token callbacks (contained "
    "per-request, never poisoning the shared wave loop)")
# paged KV cache (serving/paged): pool pressure + prefix-cache efficacy
_CACHE_BLOCKS_USED = telemetry.gauge(
    "serving_cache_blocks_used",
    "KV-cache blocks currently referenced by live requests (paged "
    "engine block pool)")
_CACHE_BLOCKS_TOTAL = telemetry.gauge(
    "serving_cache_blocks_total",
    "Usable KV-cache blocks in the paged engine's pool (scratch "
    "excluded) — used/total is the utilization that replaces dense "
    "slot occupancy")
_PREFIX_HITS = telemetry.counter(
    "serving_prefix_cache_hits_total",
    "Full prompt blocks served from the hash-based prefix cache "
    "(shared system prompts dedupe onto the same physical blocks)")
_PREFIX_MISSES = telemetry.counter(
    "serving_prefix_cache_misses_total",
    "Full prompt blocks that had to be computed by prefill (no cached "
    "block with a matching chain hash)")


def record_block_usage(used, total):
    """Export the paged pool's occupancy (called by BlockPool on every
    alloc/release)."""
    _CACHE_BLOCKS_USED.set(int(used))
    _CACHE_BLOCKS_TOTAL.set(int(total))


def record_prefix_lookup(hits, misses):
    """Count one admission's prefix-cache outcome, block-granular."""
    if hits:
        _PREFIX_HITS.inc(int(hits))
    if misses:
        _PREFIX_MISSES.inc(int(misses))


def record_callback_error(request, error):
    """Count + journal a contained client-callback exception (called
    from Request._emit — client bugs stay visible without breaking the
    per-request isolation that swallows them)."""
    _CALLBACK_ERRORS.inc()
    rec = flight_recorder.get_recorder()
    if rec is not None:
        rec.fault(kind="callback_error", action="contained",
                  request_id=request.request_id, error=repr(error))


class ServingMetrics:
    """Per-engine aggregation on top of the process-wide sinks: bounded
    TTFT/latency histograms (for this instance's p50/p99) and the
    occupancy integral (active-slot-waves / total-slot-waves)."""

    def __init__(self, num_slots):
        self.num_slots = num_slots
        self._lock = threading.Lock()
        # instance-local (unregistered) histograms: a fresh Scheduler
        # gets fresh percentiles while the registered process-wide
        # histograms keep accumulating for /metrics
        self._ttft = telemetry.Histogram(
            "serving_ttft_seconds", buckets=telemetry.DEFAULT_LATENCY_BUCKETS)
        self._latency = telemetry.Histogram(
            "serving_request_latency_seconds",
            buckets=telemetry.DEFAULT_LATENCY_BUCKETS)
        self._active_slot_waves = 0
        self._total_slot_waves = 0
        self._tokens = 0
        self._queue_peak = 0
        self._first_token_time = None
        self._last_token_time = None
        self._faults = {}
        self._rejected = 0
        self._wave_retries = 0
        # paged-pool tracking (None until a paged engine reports):
        # utilization is the block-wave integral — the paged analog of
        # slot occupancy — and the prefix tallies are deltas of the
        # pool's monotonic counters over THIS instance's lifetime
        self._block_used_waves = 0
        self._block_total_waves = 0
        self._prefix_base = None
        self._prefix_last = None

    # ---------------------------------------------------------- recording
    def on_submit(self):
        monitor.stat_add(REQUESTS_SUBMITTED)
        _REQUESTS.labels(state="submitted").inc()

    def on_reject(self):
        monitor.stat_add(REQUESTS_REJECTED)
        _REQUESTS.labels(state="rejected").inc()
        _REJECTED.inc()
        with self._lock:
            self._rejected += 1

    def on_fault(self, kind):
        _FAULTS.labels(kind=kind).inc()
        with self._lock:
            self._faults[kind] = self._faults.get(kind, 0) + 1

    def on_wave_retry(self):
        _WAVE_RETRIES.inc()
        with self._lock:
            self._wave_retries += 1

    def on_prefill(self):
        monitor.stat_add(PREFILLS)
        _PREFILLS.inc()

    def on_wave(self, n_active):
        monitor.stat_add(DECODE_WAVES)
        monitor.stat_set(SLOTS_ACTIVE, int(n_active))
        _WAVES.inc()
        _SLOTS_ACTIVE.set(int(n_active))
        with self._lock:
            self._active_slot_waves += int(n_active)
            self._total_slot_waves += self.num_slots

    def on_queue_depth(self, depth):
        monitor.stat_set(QUEUE_DEPTH, int(depth))
        monitor.stat_max(QUEUE_DEPTH_PEAK, int(depth))  # process-wide peak
        _QUEUE_DEPTH.set(int(depth))
        with self._lock:
            self._queue_peak = max(self._queue_peak, int(depth))

    def on_blocks(self, used, total):
        """One scheduling round's paged-pool occupancy sample."""
        with self._lock:
            self._block_used_waves += int(used)
            self._block_total_waves += int(total)

    def on_prefix_totals(self, hits, misses):
        """Track the pool's monotonic prefix counters; snapshot reports
        the delta across this metrics instance (per-load-point rates in
        the bench, which builds a fresh Scheduler per point)."""
        with self._lock:
            if self._prefix_base is None:
                self._prefix_base = (int(hits), int(misses))
            self._prefix_last = (int(hits), int(misses))

    def on_token(self, t_now):
        monitor.stat_add(TOKENS_GENERATED)
        _TOKENS.inc()
        with self._lock:
            self._tokens += 1
            if self._first_token_time is None:
                self._first_token_time = t_now
            self._last_token_time = t_now

    def on_complete(self, request):
        monitor.stat_add(REQUESTS_COMPLETED)
        _REQUESTS.labels(state="completed").inc()
        if request.ttft is not None:
            self._ttft.observe(request.ttft)
            _TTFT.observe(request.ttft)
        if request.latency is not None:
            self._latency.observe(request.latency)
            _LATENCY.observe(request.latency)

    # ---------------------------------------------------------- reporting
    def snapshot(self):
        """Point-in-time summary dict (the bench script serializes this).
        Keys are byte-compatible with the raw-sample-list era; the
        percentiles are now bucket-interpolated estimates."""
        with self._lock:
            active, total = self._active_slot_waves, self._total_slot_waves
            tokens = self._tokens
            first_t, last_t = (self._first_token_time,
                               self._last_token_time)
            span = (None if first_t is None or last_t is None
                    else last_t - first_t)
            queue_peak = self._queue_peak
            faults = dict(self._faults)
            rejected, wave_retries = self._rejected, self._wave_retries
            blk_used, blk_total = (self._block_used_waves,
                                   self._block_total_waves)
            if self._prefix_base is None:
                p_hits = p_misses = 0
            else:
                p_hits = self._prefix_last[0] - self._prefix_base[0]
                p_misses = self._prefix_last[1] - self._prefix_base[1]
        return {
            "requests_completed": self._latency.count(),
            "tokens_generated": tokens,
            "tokens_per_s": (tokens / span if span else None),
            "ttft_p50_s": self._ttft.percentile(50),
            "ttft_p99_s": self._ttft.percentile(99),
            "latency_p50_s": self._latency.percentile(50),
            "latency_p99_s": self._latency.percentile(99),
            "slot_occupancy": (active / total if total else 0.0),
            "queue_depth_peak": queue_peak,   # this instance, not the
                                              # process-wide monitor stat
            # resilience tallies (this instance): shedding onset vs
            # offered load shows up in bench rows through these
            "faults": faults,
            "rejected": rejected,
            "wave_retries": wave_retries,
            # paged KV pool (None/0 on a dense engine): utilization is
            # the block-wave integral — HBM held by ACTUAL tokens, the
            # number that replaces dense slot occupancy
            "block_utilization": (blk_used / blk_total if blk_total
                                  else None),
            "prefix_hits": p_hits,
            "prefix_misses": p_misses,
            "prefix_hit_rate": (p_hits / (p_hits + p_misses)
                                if p_hits + p_misses else None),
            # fleet PR: raw span endpoints (monotonic clock), so a
            # multi-replica rollup can compute the FLEET's first-to-
            # last-token span (max(last) - min(first)) and keep its
            # tokens/s denominator comparable with single-engine rows
            "first_token_time": first_t,
            "last_token_time": last_t,
        }
