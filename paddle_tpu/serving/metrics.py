"""Serving metrics: TTFT distribution, token throughput, queue depth and
slot occupancy — wired through the process-wide monitor stat registry
(utils/monitor.py) so `paddle_tpu.utils.monitor.all_stats()` shows the
serving counters next to everything else, and through
utils/profiler.RecordEvent so prefill/decode waves land in the host
profiler table and chrome traces.
"""
import threading

from ..utils import monitor

# stat-registry keys (monitor.stat_get / all_stats)
REQUESTS_SUBMITTED = "serving_requests_submitted"
REQUESTS_COMPLETED = "serving_requests_completed"
REQUESTS_REJECTED = "serving_requests_rejected"
TOKENS_GENERATED = "serving_tokens_generated"
PREFILLS = "serving_prefills"
DECODE_WAVES = "serving_decode_waves"
QUEUE_DEPTH = "serving_queue_depth"
SLOTS_ACTIVE = "serving_slots_active"
QUEUE_DEPTH_PEAK = "serving_queue_depth_peak"


class ServingMetrics:
    """Per-engine aggregation on top of the global counters: keeps the
    raw TTFT/latency samples (for p50/p99) and the occupancy integral
    (active-slot-waves / total-slot-waves)."""

    def __init__(self, num_slots):
        self.num_slots = num_slots
        self._lock = threading.Lock()
        self._ttft = []
        self._latency = []
        self._active_slot_waves = 0
        self._total_slot_waves = 0
        self._tokens = 0
        self._queue_peak = 0
        self._first_token_time = None
        self._last_token_time = None

    # ---------------------------------------------------------- recording
    def on_submit(self):
        monitor.stat_add(REQUESTS_SUBMITTED)

    def on_reject(self):
        monitor.stat_add(REQUESTS_REJECTED)

    def on_prefill(self):
        monitor.stat_add(PREFILLS)

    def on_wave(self, n_active):
        monitor.stat_add(DECODE_WAVES)
        monitor.stat_set(SLOTS_ACTIVE, int(n_active))
        with self._lock:
            self._active_slot_waves += int(n_active)
            self._total_slot_waves += self.num_slots

    def on_queue_depth(self, depth):
        monitor.stat_set(QUEUE_DEPTH, int(depth))
        monitor.stat_max(QUEUE_DEPTH_PEAK, int(depth))  # process-wide peak
        with self._lock:
            self._queue_peak = max(self._queue_peak, int(depth))

    def on_token(self, t_now):
        monitor.stat_add(TOKENS_GENERATED)
        with self._lock:
            self._tokens += 1
            if self._first_token_time is None:
                self._first_token_time = t_now
            self._last_token_time = t_now

    def on_complete(self, request):
        monitor.stat_add(REQUESTS_COMPLETED)
        with self._lock:
            if request.ttft is not None:
                self._ttft.append(request.ttft)
            if request.latency is not None:
                self._latency.append(request.latency)

    # ---------------------------------------------------------- reporting
    @staticmethod
    def _pct(samples, q):
        if not samples:
            return None
        s = sorted(samples)
        idx = min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1))))
        return s[idx]

    def snapshot(self):
        """Point-in-time summary dict (the bench script serializes this)."""
        with self._lock:
            ttft = list(self._ttft)
            lat = list(self._latency)
            active, total = self._active_slot_waves, self._total_slot_waves
            tokens = self._tokens
            span = (None if self._first_token_time is None
                    or self._last_token_time is None
                    else self._last_token_time - self._first_token_time)
            queue_peak = self._queue_peak
        return {
            "requests_completed": len(lat),
            "tokens_generated": tokens,
            "tokens_per_s": (tokens / span if span else None),
            "ttft_p50_s": self._pct(ttft, 50),
            "ttft_p99_s": self._pct(ttft, 99),
            "latency_p50_s": self._pct(lat, 50),
            "latency_p99_s": self._pct(lat, 99),
            "slot_occupancy": (active / total if total else 0.0),
            "queue_depth_peak": queue_peak,   # this instance, not the
        }                                     # process-wide monitor stat
