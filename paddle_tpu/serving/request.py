"""Request lifecycle for the serving engine.

A request moves QUEUED -> PREFILL -> DECODE -> DONE (or REJECTED at
admission). Tokens stream to the caller through an optional per-request
callback fired as each wave's tokens land on host; timestamps are taken
at every transition so TTFT/latency metrics need no extra bookkeeping.

Every request carries a `trace_id`; each lifecycle transition emits a
chrome-trace async span + flow event through utils.telemetry (no-op
unless the host profiler is recording), so an exported trace shows the
request's QUEUED/PREFILL/DECODE spans alongside the decode-wave slices
(docs/observability.md).
"""
import threading
import time

from ..utils import chaos, telemetry
from . import metrics as serving_metrics


class RequestState:
    QUEUED = "QUEUED"
    PREFILL = "PREFILL"
    DECODE = "DECODE"
    DONE = "DONE"
    REJECTED = "REJECTED"


class Request:
    """One generation request.

    prompt: list/array of int token ids (length >= 1)
    max_tokens: generation budget (>= 1); the engine also stops at the
        cache horizon (finish_reason "length") and at eos_token_id
        (finish_reason "eos"). timeout (seconds, wall-clock from submit)
        retires a stuck request with finish_reason "timeout".
    on_token: optional fn(request, token_id) streaming callback —
        exceptions are contained into `callback_error` (counted in
        `serving_callback_errors_total` and journaled) so one client
        cannot poison the shared decode loop.
    top_k / top_p: per-request sampling truncation knobs (0 / 1.0 =
        off), applied after temperature by the engines' ONE shared
        sampling tail — dense, paged, and speculative waves all honor
        them.
    stop_sequences: list of token-id sequences; the request retires
        with finish_reason "stop" as soon as its output ends with any
        of them (the matched sequence is delivered, host-side check —
        a speculative wave's multi-token batch truncates at the match).
    logit_bias: {token_id: additive bias} dict, a [V] float array, or a
        [V] bool ALLOWED mask — folded into the logits before
        selection (use -1e9 / False to forbid tokens).
    token_mask: callable(request) -> [V] bool allowed-mask or [V]
        float bias, re-evaluated before EVERY wave (constrained/JSON
        decoding: the legal set follows the tokens already emitted).
        Lanes with a dynamic mask decode one token per wave even on a
        speculative engine — drafting ahead of a mask that depends on
        unemitted tokens would break exactness.
    tenant / priority: multi-tenant QoS cohort and preemption rank
        (serving/fleet/qos.py); defaults bill the implicit "default"
        tenant at priority 0, which reproduces pre-QoS behavior
        exactly.
    handoff: block-level KV payload from a prefill-role replica
        (PagedServingEngine.export_slot_kv) — admission imports the
        blocks instead of re-running prefill chunks.
    """
    _ids = iter(range(1, 1 << 62))
    _ids_lock = threading.Lock()

    def __init__(self, prompt, max_tokens=16, eos_token_id=None,
                 timeout=None, on_token=None, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0,
                 stop_sequences=None, logit_bias=None, token_mask=None,
                 stop_context=None, trace_id=None, tenant="default",
                 priority=0, handoff=None):
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        with Request._ids_lock:
            self.request_id = next(Request._ids)
        # correlates trace events; a migrating FleetRequest passes ITS
        # fleet-scoped id so every hop's spans/flows share one chrome
        # flow across replicas (one linked trace, not one per hop)
        self.trace_id = (self.request_id if trace_id is None
                         else int(trace_id))
        self.trace_pid = 0               # chrome process row (fleet:
                                         # replica_id + 1, set at submit)
        self.prompt = prompt
        self.max_tokens = int(max_tokens)
        self.eos_token_id = None if eos_token_id is None else int(eos_token_id)
        self.timeout = None if timeout is None else float(timeout)
        self.on_token = on_token
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.stop_sequences = [
            [int(t) for t in seq] for seq in (stop_sequences or [])
            if len(seq)]
        # tokens that PRECEDE this request's output stream for stop
        # matching: a fleet migration folds the dead hop's tokens into
        # the continuation PROMPT, so a stop sequence straddling the
        # seam would be invisible to the hop-local output — the router
        # passes the prior stream's tail here (_submit_kwargs)
        self._stop_context = [int(t) for t in (stop_context or [])]
        self.logit_bias = logit_bias
        self.token_mask = token_mask
        # multi-tenant QoS surface: the cohort this request bills
        # against (weighted-fair admission under pool pressure, per-
        # tenant SLO attainment) and its preemption priority — under
        # block starvation the scheduler evicts the lowest-priority
        # lane STRICTLY below the starved one, never a peer or better
        self.tenant = str(tenant)
        self.priority = int(priority)
        # a block-level KV handoff payload (engine.export_slot_kv):
        # admission imports the populated blocks instead of running
        # prefill chunks; consumed one-shot at the first admission so
        # any LATER re-admission (preemption, migration) replays
        # normally from the prefix cache
        self.handoff = handoff
        # resolved sampling-seed provenance: the scheduler stamps the
        # engine's PRNG-chain seed here at submission (greedy requests
        # too — the chain is shared), so a journaled sampled request
        # names the seed that replays it (serving/blackbox.py)
        self.seed = None

        self.state = RequestState.QUEUED
        self.slot = None                 # engine slot while PREFILL/DECODE
        # paged engine: times this request was preempted by recompute
        # (KV blocks reclaimed under pool pressure, request requeued
        # with prompt + generated tokens; bounded by the scheduler's
        # max_preemptions)
        self.preemptions = 0
        # scheduler-private: True while this request waits at the queue
        # head for KV blocks to free — the cache_exhausted/requeued
        # fault is recorded once per wait EPISODE, not once per round
        self._cache_waiting = False
        self.output_tokens = []
        # eos | stop | max_tokens | length | timeout | error | rejected
        self.finish_reason = None
        self.error = None                # detail when error/rejected
        self.callback_error = None
        self.submit_time = None          # set by the scheduler at admission
        self.prefill_time = None
        self.first_token_time = None
        self.last_token_time = None      # stamped per emitted token —
                                         # TPOT (inter-token latency)
                                         # derives from first/last
        self.done_time = None
        self._done_event = threading.Event()

    # ------------------------------------------------------------ lifecycle
    def _mark_submitted(self):
        self.submit_time = time.monotonic()
        telemetry.trace_request(self, RequestState.QUEUED)

    def _start_prefill(self, slot):
        self.state = RequestState.PREFILL
        self.slot = slot
        self.prefill_time = time.monotonic()
        telemetry.trace_request(self, RequestState.PREFILL)

    def _emit(self, token_id):
        """Record one generated token (first one comes from prefill)."""
        token_id = int(token_id)
        now = time.monotonic()
        if self.first_token_time is None:
            self.first_token_time = now
        self.last_token_time = now
        if self.state != RequestState.DECODE:
            # also re-entered after preemption-by-recompute: the resumed
            # request passed through PREFILL again with first_token_time
            # already stamped, and must still come back to DECODE
            self.state = RequestState.DECODE
            telemetry.trace_request(self, RequestState.DECODE)
        self.output_tokens.append(token_id)
        if self.on_token is not None:
            try:
                if chaos.enabled():
                    chaos.fire(chaos.CALLBACK, request_id=self.request_id)
                self.on_token(self, token_id)
            except Exception as e:    # noqa: BLE001 — client code
                self.callback_error = e
                serving_metrics.record_callback_error(self, e)

    def _finish(self, reason, error=None):
        self.state = RequestState.DONE
        self.finish_reason = reason
        if error is not None:
            self.error = str(error)
        self.slot = None
        self.done_time = time.monotonic()
        telemetry.trace_request(self, RequestState.DONE, reason=reason)
        self._done_event.set()

    def _fail(self, error):
        """Resolve this request with finish_reason "error" (fault
        isolation: the poisoned/failed request ends cleanly while the
        rest of the batch keeps decoding)."""
        self._finish("error", error=error)

    def _reject(self, why, raise_error=True):
        """Shed at admission (finish_reason "rejected"). Raises to the
        submitting caller by default; the scheduler's degrade path
        resolves already-queued requests with raise_error=False."""
        self.state = RequestState.REJECTED
        self.finish_reason = "rejected"
        self.error = str(why)
        self.done_time = time.monotonic()
        telemetry.trace_request(self, RequestState.REJECTED)
        self._done_event.set()
        if raise_error:
            raise ValueError(why)

    def _timed_out(self):
        return (self.timeout is not None and self.submit_time is not None
                and time.monotonic() - self.submit_time > self.timeout)

    def _hit_stop(self):
        """True when the output stream ends with one of the request's
        stop sequences (checked after every emitted token — host-side,
        so every engine flavour gets stop sequences for free). The
        stream is stop_context + output_tokens, so a sequence
        straddling a migration seam still matches; a match that lies
        entirely inside the context (already delivered by a prior hop)
        never re-fires because this runs only after a NEW token."""
        out = self._stop_context + self.output_tokens
        for seq in self.stop_sequences:
            if len(out) >= len(seq) and out[-len(seq):] == seq:
                return True
        return False

    # ------------------------------------------------------------ client API
    @property
    def done(self):
        return self.state in (RequestState.DONE, RequestState.REJECTED)

    def wait(self, timeout=None):
        """Block until DONE/REJECTED (for callers driving the scheduler
        from another thread). Returns True when the request finished,
        False when the wait timed out (threading.Event.wait semantics —
        a None-returning wait hid the difference)."""
        return self._done_event.wait(timeout)

    @property
    def ttft(self):
        """Time-to-first-token in seconds (None until the first token)."""
        if self.first_token_time is None or self.submit_time is None:
            return None
        return self.first_token_time - self.submit_time

    @property
    def latency(self):
        if self.done_time is None or self.submit_time is None:
            return None
        return self.done_time - self.submit_time

    @property
    def tpot(self):
        """Mean time-per-output-token in seconds: the inter-token span
        divided by the gap count. None until a second token exists (the
        first token's latency is TTFT, not TPOT)."""
        n = len(self.output_tokens)
        if n < 2 or self.first_token_time is None \
                or self.last_token_time is None:
            return None
        return (self.last_token_time - self.first_token_time) / (n - 1)

    def __repr__(self):
        return (f"Request(id={self.request_id}, state={self.state}, "
                f"tenant={self.tenant!r}, seed={self.seed}, "
                f"prompt_len={len(self.prompt)}, "
                f"generated={len(self.output_tokens)}/{self.max_tokens}, "
                f"finish={self.finish_reason})")
