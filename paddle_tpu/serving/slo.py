"""SLO engine: declarative latency/error targets, sliding-window
attainment, and error-budget burn rate.

Queue depth says how much work is WAITING; it says nothing about
whether the fleet is meeting a latency promise. This module turns the
serving stack's own measurements (TTFT, TPOT, finish_reason) into the
SRE vocabulary an autoscaler can act on:

  * `SLOPolicy` — the declarative contract: p99-style targets
    (`ttft_p99_s`, `tpot_p99_s`), an error-rate budget, the objective
    (what fraction of requests must meet each latency target), and the
    burn thresholds the autoscaler reacts to.
  * `SLOEngine` — a sliding window of completed requests evaluated
    against the policy. For each target the **error budget** is
    `1 - objective` (for the error target, the `error_rate` itself) and
    the **burn rate** is `bad_fraction / budget`: 1.0 means exactly
    spending budget, >1 burning it, `fast_burn` (default 2.0) is the
    page-the-oncall threshold. The engine's verdict is the WORST
    target's burn.

Wiring (all optional, nothing changes when no policy is configured):

  * `Scheduler(slo=policy)` observes every completion and re-evaluates
    each round; the verdict rides the engine's `/healthz` payload.
  * `FleetRouter(slo=policy)` observes finalized fleet requests and its
    autoscaler consumes the burn rate — scale up on fast burn, drain
    the newest replica on sustained surplus — instead of raw queue
    depth (the no-SLO fleet keeps the queue-depth behavior).
  * Burn-rate transitions (alert/clear, scale actions) are journaled
    through the current flight recorder as `slo` events and exported as
    the `slo_burn_rate` / `slo_attainment` gauges.

The engine is pure host-side bookkeeping — it never touches compiled
programs, so the compile-once discipline is untouched by SLO tracking.
"""
import collections
import threading
import time

from ..utils import flight_recorder, telemetry

_BURN = telemetry.gauge(
    "slo_burn_rate",
    "Error-budget burn rate per SLO target (bad-fraction / budget over "
    "the sliding window; 1.0 = spending exactly the budget, the fleet "
    "autoscaler scales up past the policy's fast_burn threshold)",
    labelnames=("slo",))
_ATTAINMENT = telemetry.gauge(
    "slo_attainment",
    "Fraction of windowed requests meeting each SLO target (1.0 = "
    "every request within target)",
    labelnames=("slo",))

#: the closed label set for the gauges above — one series per target
#: plus the overall (worst-target) verdict
TARGETS = ("ttft_p99", "tpot_p99", "error_rate", "overall")


class SLOPolicy:
    """Declarative serving SLO.

    ttft_p99_s / tpot_p99_s: latency targets in seconds — a request is
        "good" for the target when its measured TTFT / mean TPOT is
        within it. `objective` is the fraction of requests that must be
        good (0.99 = a 1% error budget).
    error_rate: budget for requests resolving finish_reason "error"
        (0.01 = 1% may fail before the budget burns).
    window_s: sliding evaluation window (seconds).
    fast_burn: burn rate at/above which the SLO is BREACHED (alerting +
        fleet scale-up). slow_burn: burn rate at/below which the fleet
        has sustained surplus (scale-down candidate).
    cooldown_rounds: fleet rounds between burn-driven scale-ups, so one
        long breach adds replicas stepwise instead of all at once.
    """

    def __init__(self, ttft_p99_s=None, tpot_p99_s=None, error_rate=None,
                 objective=0.99, window_s=60.0, fast_burn=2.0,
                 slow_burn=0.5, cooldown_rounds=4):
        if ttft_p99_s is None and tpot_p99_s is None and error_rate is None:
            raise ValueError("an SLOPolicy needs at least one target "
                             "(ttft_p99_s, tpot_p99_s, or error_rate)")
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), "
                             f"got {objective}")
        if error_rate is not None and not 0.0 < error_rate <= 1.0:
            raise ValueError(f"error_rate must be in (0, 1], "
                             f"got {error_rate}")
        if fast_burn <= slow_burn:
            raise ValueError(f"fast_burn ({fast_burn}) must exceed "
                             f"slow_burn ({slow_burn})")
        self.ttft_p99_s = None if ttft_p99_s is None else float(ttft_p99_s)
        self.tpot_p99_s = None if tpot_p99_s is None else float(tpot_p99_s)
        self.error_rate = None if error_rate is None else float(error_rate)
        self.objective = float(objective)
        self.window_s = float(window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.cooldown_rounds = max(0, int(cooldown_rounds))

    def describe(self):
        """The policy as a flat dict (health payloads, bench rows)."""
        return {"ttft_p99_s": self.ttft_p99_s,
                "tpot_p99_s": self.tpot_p99_s,
                "error_rate": self.error_rate,
                "objective": self.objective,
                "window_s": self.window_s,
                "fast_burn": self.fast_burn,
                "slow_burn": self.slow_burn}


class SLOEngine:
    """Sliding-window SLO evaluation over completed requests.

    Thread-model: `observe*` is called from whichever thread drives the
    scheduler/router loop; `evaluate()`/`health()` may be called from
    exporter threads — everything mutable sits under one lock.
    """

    def __init__(self, policy, clock=time.monotonic):
        if not isinstance(policy, SLOPolicy):
            raise TypeError(f"policy must be an SLOPolicy, "
                            f"got {type(policy).__name__}")
        self.policy = policy
        self._clock = clock
        self._lock = threading.Lock()
        self._window = collections.deque()   # (t, ttft, tpot, error)
        self._last = None                    # latest verdict dict
        self._breached = False
        self.peak_burn_rate = 0.0

    # ---------------------------------------------------------- recording
    def observe_request(self, request):
        """Fold one finished request in (duck-typed: `.ttft`, `.tpot`,
        `.finish_reason` — both replica-local Requests and fleet-level
        FleetRequests qualify)."""
        self.observe(ttft=request.ttft, tpot=request.tpot,
                     error=(request.finish_reason == "error"))

    def observe(self, ttft=None, tpot=None, error=False, t=None):
        t = self._clock() if t is None else float(t)
        with self._lock:
            self._window.append((
                t,
                None if ttft is None else float(ttft),
                None if tpot is None else float(tpot),
                bool(error)))

    def reset(self):
        """Fresh window + peak (the bench evaluates load points
        independently). The policy and gauge registrations stay."""
        with self._lock:
            self._window.clear()
            self._last = None
            self._breached = False
            self.peak_burn_rate = 0.0

    # --------------------------------------------------------- evaluation
    def _prune(self, now):
        horizon = now - self.policy.window_s
        while self._window and self._window[0][0] < horizon:
            self._window.popleft()

    @staticmethod
    def _target_verdict(vals, target, budget):
        """(burn, attainment, n) for one latency target: `vals` are the
        requests that produced a measurement; an empty window spends no
        budget (burn 0, attainment 1)."""
        vals = [v for v in vals if v is not None]
        if not vals:
            return 0.0, 1.0, 0
        bad = sum(1 for v in vals if v > target)
        frac = bad / len(vals)
        return frac / budget, 1.0 - frac, len(vals)

    def evaluate(self, now=None, publish=True):
        """One verdict over the current window:

        {"burn_rate", "attainment", "breached", "worst", "window_requests",
         "targets": {name: {"burn_rate", "attainment", "requests"}}}

        `burn_rate` is the worst target's; `breached` latches against
        the policy's fast_burn. With publish=True (the scheduler/router
        loop) the gauges are updated and alert/clear TRANSITIONS are
        journaled through the current flight recorder; health probes use
        the cached verdict and never publish."""
        pol = self.policy
        now = self._clock() if now is None else float(now)
        with self._lock:
            self._prune(now)
            samples = list(self._window)
        budget = max(1e-9, 1.0 - pol.objective)
        targets = {}
        if pol.ttft_p99_s is not None:
            b, a, n = self._target_verdict(
                [s[1] for s in samples], pol.ttft_p99_s, budget)
            targets["ttft_p99"] = {"burn_rate": b, "attainment": a,
                                   "requests": n}
        if pol.tpot_p99_s is not None:
            b, a, n = self._target_verdict(
                [s[2] for s in samples], pol.tpot_p99_s, budget)
            targets["tpot_p99"] = {"burn_rate": b, "attainment": a,
                                   "requests": n}
        if pol.error_rate is not None:
            n = len(samples)
            bad = sum(1 for s in samples if s[3])
            frac = bad / n if n else 0.0
            targets["error_rate"] = {"burn_rate": frac / pol.error_rate,
                                     "attainment": 1.0 - frac,
                                     "requests": n}
        worst = max(targets, key=lambda k: targets[k]["burn_rate"],
                    default=None)
        burn = targets[worst]["burn_rate"] if worst else 0.0
        attainment = min((t["attainment"] for t in targets.values()),
                         default=1.0)
        verdict = {
            "burn_rate": burn,
            "attainment": attainment,
            "breached": burn >= pol.fast_burn,
            "worst": worst,
            "window_requests": len(samples),
            "targets": targets,
        }
        if publish:
            self._publish(verdict)
        with self._lock:
            self._last = verdict
            self.peak_burn_rate = max(self.peak_burn_rate, burn)
        return verdict

    def _publish(self, verdict):
        for name, t in verdict["targets"].items():
            _BURN.labels(slo=name).set(t["burn_rate"])
            _ATTAINMENT.labels(slo=name).set(t["attainment"])
        _BURN.labels(slo="overall").set(verdict["burn_rate"])
        _ATTAINMENT.labels(slo="overall").set(verdict["attainment"])
        breached = verdict["breached"]
        if breached != self._breached:
            self._breached = breached
            self._journal("burn_alert" if breached else "burn_clear",
                          verdict)

    def _journal(self, action, verdict, **extra):
        rec = flight_recorder.get_recorder()
        if rec is not None:
            rec.slo(burn_rate=verdict["burn_rate"], action=action,
                    attainment=verdict["attainment"],
                    slo=verdict["worst"],
                    window_requests=verdict["window_requests"], **extra)

    def journal_scale(self, direction, verdict, replicas):
        """The fleet autoscaler acted on this engine's burn rate —
        journal the action next to the alert that caused it."""
        self._journal("scale_" + direction, verdict, replicas=replicas)

    # ---------------------------------------------------------- reporting
    @property
    def last_verdict(self):
        with self._lock:
            return self._last

    def health(self):
        """The /healthz satellite payload: the policy's targets plus the
        latest verdict (computed lazily, never published — a dashboard
        poll must not mint journal entries or move gauges)."""
        verdict = self.last_verdict
        if verdict is None:
            verdict = self.evaluate(publish=False)
        return {"slo": {
            "burn_rate": round(verdict["burn_rate"], 4),
            "attainment": round(verdict["attainment"], 6),
            "breached": verdict["breached"],
            "worst": verdict["worst"],
            "window_requests": verdict["window_requests"],
            "targets": self.policy.describe(),
        }}

    def summary(self):
        """Compact rollup for bench rows: latest verdict + the peak
        burn over this engine's lifetime (reset() starts a new one)."""
        verdict = self.last_verdict
        if verdict is None:
            verdict = self.evaluate(publish=False)
        with self._lock:
            peak = self.peak_burn_rate
        return {"attainment": round(verdict["attainment"], 6),
                "burn_rate": round(verdict["burn_rate"], 4),
                "burn_rate_peak": round(peak, 4),
                "window_requests": verdict["window_requests"]}


def as_engine(slo):
    """Normalize a Scheduler/FleetRouter `slo=` argument: None passes
    through, an SLOPolicy is wrapped, an SLOEngine is used as-is (NOT
    shared implicitly — pass one engine to several consumers only when
    a merged window is what you mean)."""
    if slo is None or isinstance(slo, SLOEngine):
        return slo
    return SLOEngine(slo)
