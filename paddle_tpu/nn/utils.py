"""paddle.nn.utils (ref python/paddle/nn/utils/weight_norm_hook.py,
spectral_norm_hook.py, transform_parameters.py): layer reparametrization
hooks + parameter/vector converters."""
import numpy as np
import jax.numpy as jnp

from ..framework.tensor import Tensor, Parameter
from .layer import Layer


def _norm_except(v, dim):
    """||v|| computed over every axis except `dim` (None = whole tensor),
    shaped to broadcast against v."""
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    dim = dim % v.ndim          # negative dims must still exclude an axis
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparametrize layer.<name> as g * v / ||v|| (ref weight_norm_hook):
    the original parameter is replaced by `<name>_g` (magnitude) and
    `<name>_v` (direction); a forward-pre-hook recomputes the composed
    weight every call, so the optimizer trains g and v."""
    if getattr(layer, f"__wn_{name}", None):
        raise ValueError(f"weight_norm already applied to {name!r}")
    w = getattr(layer, name)
    warr = w._data
    g0 = _norm_except(warr, dim)
    g = Parameter(g0, name=(w.name or name) + "_g")
    v = Parameter(jnp.copy(warr), name=(w.name or name) + "_v")
    # unregister the original parameter; Layer.__setattr__ registers the
    # new pair into _parameters (single source of truth — no __dict__
    # mirrors to go stale)
    del layer._parameters[name]
    setattr(layer, name + "_g", g)
    setattr(layer, name + "_v", v)

    def compose():
        vv = getattr(layer, name + "_v")
        gg = getattr(layer, name + "_g")
        # keep everything in Tensor space so grads flow to g and v
        from ..ops.dispatch import apply

        def f(v_, g_):
            return v_ * (g_ / _norm_except(v_, dim))

        return apply(f, (vv, gg), name="weight_norm")

    def pre_hook(lyr, inputs):
        setattr(lyr, name, compose())
        return inputs

    handle = layer.register_forward_pre_hook(pre_hook)
    object.__setattr__(layer, f"__wn_{name}", (handle, dim))
    setattr(layer, name, compose())             # usable before a forward
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g * v/||v|| back into a single parameter (ref remove hook)."""
    state = getattr(layer, f"__wn_{name}", None)
    if not state:
        raise ValueError(f"weight_norm was not applied to {name!r}")
    handle, dim = state
    handle.remove()
    g = layer._parameters.pop(name + "_g")
    v = layer._parameters.pop(name + "_v")
    composed = v._data * (g._data / _norm_except(v._data, dim))
    p = Parameter(composed, name=v.name[:-2] if v.name else name)
    layer.__dict__.pop(name, None)   # drop the composed-Tensor shadow
    setattr(layer, name, p)          # re-registers into _parameters
    object.__setattr__(layer, f"__wn_{name}", None)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Divide layer.<name> by its spectral norm every forward (ref
    spectral_norm_hook; persistent power-iteration state rides the
    SpectralNorm module and advances on every eager call)."""
    from .norm import SpectralNorm
    if getattr(layer, f"__sn_{name}", None):
        raise ValueError(f"spectral_norm already applied to {name!r}")
    w = getattr(layer, name)
    if dim is None:
        # ref spectral_norm_hook: Linear and transpose convs matricize
        # along dim 1 (their weight layout puts the output axis second)
        cls = type(layer).__name__
        dim = 1 if (("Linear" in cls or "Transpose" in cls)
                    and len(w.shape) > 1) else 0
    sn = SpectralNorm(tuple(w.shape), dim=dim,
                      power_iters=n_power_iterations, eps=eps)
    layer.add_sublayer(f"_spectral_norm_{name}", sn)
    orig = layer._parameters[name]

    def pre_hook(lyr, inputs):
        object.__setattr__(lyr, name, sn(orig))
        return inputs

    handle = layer.register_forward_pre_hook(pre_hook)
    object.__setattr__(layer, f"__sn_{name}", (handle, dim))
    return layer


def parameters_to_vector(parameters, name=None):
    """Concatenate parameters into one flat Tensor (ref
    transform_parameters.py)."""
    arrs = [p._data.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(arrs) if arrs
                  else jnp.zeros((0,), jnp.float32))


def vector_to_parameters(vec, parameters):
    """Write a flat vector back into the parameter list (in-place)."""
    data = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    total = sum(int(np.prod(p.shape)) for p in parameters)
    if total != data.size:
        raise ValueError(
            f"vector has {data.size} elements but parameters need {total}")
    for p in parameters:
        k = int(np.prod(p.shape))
        p._data = data[off:off + k].reshape(tuple(p.shape)) \
            .astype(p._data.dtype)
        off += k
