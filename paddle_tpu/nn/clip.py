"""Gradient clipping (ref python/paddle/fluid/clip.py: ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm). Operates on (param, grad) lists both
eagerly (Tensor grads) and functionally (jnp pytrees, for jit'd steps)."""
import jax.numpy as jnp

from ..framework.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError

    def apply_arrays(self, grads):
        """Functional form: list/tree of jnp arrays -> clipped arrays."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out

    def apply_arrays(self, grads):
        return [None if g is None else jnp.clip(g, self.min, self.max)
                for g in grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_one(self, g):
        norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
        return g * scale

    def __call__(self, params_grads):
        return [(p, g if g is None else Tensor(self._clip_one(g._data)))
                for p, g in params_grads]

    def apply_arrays(self, grads):
        return [None if g is None else self._clip_one(g) for g in grads]


class ClipGradByGlobalNorm(ClipGradBase):
    """ref fluid/clip.py GradientClipByGlobalNorm — the Fleet default clip."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _scale(self, arrays):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                 for g in arrays if g is not None)
        global_norm = jnp.sqrt(sq)
        return self.clip_norm / jnp.maximum(global_norm, self.clip_norm)

    def __call__(self, params_grads):
        arrays = [g._data for _, g in params_grads if g is not None]
        if not arrays:
            return params_grads
        scale = self._scale(arrays)
        return [(p, g if g is None else Tensor(g._data * scale.astype(g.dtype)))
                for p, g in params_grads]

    def apply_arrays(self, grads):
        live = [g for g in grads if g is not None]
        if not live:
            return grads
        scale = self._scale(live)
        return [None if g is None else g * scale.astype(g.dtype) for g in grads]


# fluid aliases
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
