"""Common layers: Linear, Embedding, Dropout, Flatten, padding, upsample
(ref python/paddle/nn/layer/common.py)."""
import numpy as np
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import register_op
from . import functional as F
from . import initializer as I
from .layer import Layer


def _bilinear_raw(a, b, w, *maybe_bias):
    out = jnp.einsum("bi,oij,bj->bo", a, w, b)
    return out + maybe_bias[0] if maybe_bias else out


register_op("bilinear", _bilinear_raw)


class Linear(Layer):
    """weight [in_features, out_features] like the reference (nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self._sparse = sparse
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx,
                           sparse=self._sparse)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ..ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners,
                             align_mode=self.align_mode,
                             data_format=self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size=size, scale_factor=scale_factor, mode="bilinear",
                         align_corners=True, data_format=data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size=size, scale_factor=scale_factor, mode="nearest",
                         data_format=data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([1, out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        from ..ops.dispatch import apply
        if self.bias is not None:
            return apply(_bilinear_raw, (x1, x2, self.weight, self.bias),
                         name="bilinear")
        return apply(_bilinear_raw, (x1, x2, self.weight), name="bilinear")


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    """ref paddle.nn.PairwiseDistance."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, p=self.p, epsilon=self.epsilon,
                                   keepdim=self.keepdim)


class Unfold(Layer):
    """ref paddle.nn.Unfold (im2col as a layer)."""

    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, strides=self.strides,
                        paddings=self.paddings, dilations=self.dilations)
