"""Decoding: beam search + dynamic_decode
(ref python/paddle/fluid/layers/rnn.py:1034 BeamSearchDecoder,
 :1496 dynamic_decode, paddle/fluid/operators/math/beam_search.h
 BeamSearchFunctor).

TPU-native redesign: the reference's beam_search op mutates LoD tensors per
step inside a C++ while-op; here the whole decode is ONE lax.scan with
dense [batch, beam] state — scores/finished/lengths plus a fixed
[batch, beam, max_steps] token buffer written at step t (no LoD, no
dynamic shapes; XLA unrolls nothing). Finished beams are absorbing: only
<eos> continues them at zero added cost, everything else is masked to -inf
(the reference's is_finished handling in beam_search_op).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..framework.tensor import Tensor
from ..ops.dispatch import as_array

_NEG_INF = -1e9


def _gather_beams(x, idx, B, K):
    """x: [B, K, ...] -> x[b, idx[b, k]] (re-rank beams)."""
    return jax.vmap(lambda xb, ib: xb[ib])(x, idx)


class BeamSearchDecoder:
    """ref fluid/layers/rnn.py BeamSearchDecoder. Wraps an RNN cell (or any
    callable (inputs, states) -> (cell_out, new_states)) for beam decode.

    embedding_fn maps token ids -> cell inputs; output_fn maps cell output
    -> vocab logits (defaults to identity, i.e. the cell emits logits)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # tile_beam_merge_with_batch (ref rnn.py:1112): [B, ...] -> [B*K, ...]
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        a = as_array(x)
        a = jnp.repeat(a[:, None], beam_size, axis=1)
        return Tensor(a.reshape((-1,) + a.shape[2:]))


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """Run beam-search decode (ref fluid/layers/rnn.py dynamic_decode).

    inits: initial cell states (pytree of [B, ...] arrays/Tensors).
    Returns (ids Tensor [B, max_step_num, K], lengths Tensor [B, K]) —
    beams sorted best-first, padded with end_token after finish."""
    K = decoder.beam_size
    eos = decoder.end_token
    cell = decoder.cell
    embed = decoder.embedding_fn
    out_fn = decoder.output_fn

    states0 = jax.tree.map(as_array, inits)
    B = jax.tree_util.tree_leaves(states0)[0].shape[0]

    # beam-tile cell states: [B, ...] -> [B, K, ...]
    states0 = jax.tree.map(
        lambda a: jnp.repeat(a[:, None], K, axis=1), states0)

    # beam 0 live, others dead (standard init so step0 expands one beam)
    log_probs0 = jnp.full((B, K), _NEG_INF, jnp.float32).at[:, 0].set(0.0)
    tokens0 = jnp.full((B, K), decoder.start_token, jnp.int32)
    finished0 = jnp.zeros((B, K), bool)
    lengths0 = jnp.zeros((B, K), jnp.int32)
    buf0 = jnp.full((B, K, max_step_num), eos, jnp.int32)

    def call_cell(tok, states):
        """One cell step over flattened beams."""
        flat_states = jax.tree.map(
            lambda a: a.reshape((B * K,) + a.shape[2:]), states)
        inp = tok.reshape(B * K)
        if embed is not None:
            inp = as_array(embed(Tensor(inp)))
        out, new_states = cell(Tensor(inp), jax.tree.map(Tensor, flat_states))
        logits = as_array(out_fn(out)) if out_fn is not None else as_array(out)
        new_states = jax.tree.map(
            lambda t: as_array(t).reshape((B, K) + as_array(t).shape[1:]),
            new_states)
        return logits.reshape(B, K, -1), new_states

    def step(carry, t):
        log_probs, tokens, finished, lengths, states, buf = carry
        logits, new_states = call_cell(tokens, states)
        V = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

        # finished beams: only <eos> continues, at no added cost
        eos_only = jnp.full((V,), _NEG_INF).at[eos].set(0.0)
        logp = jnp.where(finished[..., None], eos_only[None, None, :], logp)

        scores = log_probs[..., None] + logp                  # [B, K, V]
        flat = scores.reshape(B, K * V)
        top_scores, top_idx = lax.top_k(flat, K)              # [B, K]
        parent = top_idx // V
        token = (top_idx % V).astype(jnp.int32)

        new_finished = _gather_beams(finished, parent, B, K) | (token == eos)
        prev_len = _gather_beams(lengths, parent, B, K)
        was_fin = _gather_beams(finished, parent, B, K)
        new_lengths = jnp.where(was_fin, prev_len, prev_len + 1)

        states = jax.tree.map(
            lambda a: _gather_beams(a, parent, B, K), new_states)
        buf = _gather_beams(buf, parent, B, K)
        buf = buf.at[:, :, t].set(jnp.where(was_fin, eos, token))

        return (top_scores, token, new_finished, new_lengths, states,
                buf), None

    carry0 = (log_probs0, tokens0, finished0, lengths0, states0, buf0)
    (log_probs, _, finished, lengths, _, buf), _ = lax.scan(
        step, carry0, jnp.arange(max_step_num))

    # best-first by per-beam score (length-normalised like the reference's
    # final ranking on finished beams)
    norm = log_probs / jnp.maximum(lengths, 1).astype(jnp.float32)
    order = jnp.argsort(-norm, axis=1)
    buf = _gather_beams(buf, order, B, K)
    lengths = jnp.take_along_axis(lengths, order, axis=1)
    return Tensor(jnp.transpose(buf, (0, 2, 1))), Tensor(lengths)


# ----------------------------------------------------------------- sampling

def top_k_top_p_filtering(logits, top_k=0, top_p=1.0):
    """Mask logits outside top-k / nucleus top-p to -inf
    (ref generation_utils TopKProcess/TopPProcess)."""
    a = as_array(logits).astype(jnp.float32)
    if top_k and top_k > 0:
        kth = lax.top_k(a, min(int(top_k), a.shape[-1]))[0][..., -1:]
        a = jnp.where(a < kth, _NEG_INF, a)
    if top_p is not None and top_p < 1.0:
        sort_idx = jnp.argsort(-a, axis=-1)
        sorted_a = jnp.take_along_axis(a, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_a, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens with cumulative prob <= p (always keep the best),
        # then scatter the sorted mask back via the inverse permutation
        keep_sorted = cum - probs < top_p
        keep_sorted = keep_sorted.at[..., 0].set(True)
        inv = jnp.argsort(sort_idx, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
        a = jnp.where(keep, a, _NEG_INF)
    return Tensor(a)


def sampling_id(probs, seed=None, key=None):
    """Sample token ids from probability rows (ref operators/sampling_id_op.cc).
    """
    from ..framework import state
    p = as_array(probs)
    if key is None:
        key = (jax.random.PRNGKey(seed) if seed is not None
               else state.next_rng_key())
    return Tensor(jax.random.categorical(
        key, jnp.log(jnp.maximum(p, 1e-30)), axis=-1))


def greedy_search(logits):
    """argmax decode helper."""
    return Tensor(jnp.argmax(as_array(logits), axis=-1))
