"""paddle_tpu.nn — layers, functional ops, initializers
(ref python/paddle/nn/__init__.py surface)."""
from . import functional
from . import initializer
from .layer import (Layer, LayerList, Sequential, ParameterList,
                    HookRemoveHelper)
from .param_attr import ParamAttr
from .layers_common import (PairwiseDistance, Unfold,
                            Linear, Embedding, Dropout, Dropout2D, Dropout3D,
                            AlphaDropout, Flatten, Identity, Pad1D, Pad2D,
                            Pad3D, Upsample, UpsamplingBilinear2D,
                            UpsamplingNearest2D, PixelShuffle, Bilinear,
                            CosineSimilarity)
from .conv import (Conv1D, Conv2D, Conv3D, Conv2DTranspose,
                   Conv1DTranspose, Conv3DTranspose)
from .norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                   SyncBatchNorm, LayerNorm, GroupNorm, InstanceNorm1D,
                   InstanceNorm2D, InstanceNorm3D, LocalResponseNorm,
                   SpectralNorm)
from .pooling import (MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D,
                      AvgPool2D, AvgPool3D, AdaptiveAvgPool1D,
                      AdaptiveAvgPool2D, AdaptiveAvgPool3D,
                      AdaptiveMaxPool1D, AdaptiveMaxPool2D,
                      AdaptiveMaxPool3D)
from .activation import (ReLU, ReLU6, Sigmoid, Tanh, Silu, Swish, Mish,
                         Hardswish, Hardsigmoid, Softsign, Tanhshrink, GELU,
                         LeakyReLU, ELU, CELU, SELU, PReLU, Hardtanh,
                         Hardshrink, Softshrink, Softplus, Softmax, LogSoftmax,
                         Maxout, LogSigmoid, ThresholdedReLU)
from .loss import (CTCLoss,
                   CrossEntropyLoss, MSELoss, L1Loss, SmoothL1Loss, NLLLoss,
                   BCELoss, BCEWithLogitsLoss, KLDivLoss, MarginRankingLoss,
                   HingeEmbeddingLoss, HSigmoidLoss)
from .clip import ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm
from . import transformer
from . import paged_attention
from .paged_attention import (paged_chunk_attention,
                              paged_decode_attention, set_paged_kernel)
from .transformer import (MultiHeadAttention, TransformerEncoderLayer,
                          TransformerEncoder, TransformerDecoderLayer,
                          TransformerDecoder, Transformer)
from . import rnn
from .rnn import (RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN,
                  SimpleRNN, LSTM, GRU, RNNBase)
from . import decode
from .decode import (BeamSearchDecoder, dynamic_decode,
                     top_k_top_p_filtering, sampling_id, greedy_search)

from . import utils  # noqa: E402
