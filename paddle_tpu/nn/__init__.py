"""placeholder"""
