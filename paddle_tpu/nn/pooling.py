"""Pooling layers (ref python/paddle/nn/layer/pooling.py)."""
from . import functional as F
from .layer import Layer


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.ceil_mode, self.data_format = ceil_mode, data_format

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 count_include_pad=True, divisor_override=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.ceil_mode, self.count_include_pad = ceil_mode, count_include_pad
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode,
                            count_include_pad=self.count_include_pad,
                            data_format=self.data_format)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size,
                                     data_format=self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        out = F.adaptive_avg_pool2d(x.unsqueeze(-1), (self.output_size
                                                      if isinstance(self.output_size, int)
                                                      else self.output_size[0], 1))
        return out.squeeze(-1)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = (kernel_size, stride,
                                                       padding)

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 count_include_pad=True, divisor_override=None,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = (kernel_size, stride,
                                                       padding)
        self.count_include_pad = count_include_pad

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            count_include_pad=self.count_include_pad)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)
