"""Recurrent layers: SimpleRNN / LSTM / GRU cells and multi-layer RNNs.

TPU-native redesign of the reference RNN stack (ref
python/paddle/nn/layer/rnn.py:144-1400 and the cuDNN-backed rnn op,
paddle/fluid/operators/rnn_op.cu): instead of a per-timestep op loop (or a
vendor RNN kernel), a whole (layer, direction) pass is ONE registered op whose
body is

    1. input projection for ALL timesteps in a single  [T*B, I] x [I, G*H]
       matmul — the FLOPs land on the MXU in one large tile-friendly GEMM;
    2. `lax.scan` over time carrying only the small recurrent GEMM — XLA
       unrolls nothing, compiles once, and the loop body stays fused.

This makes forward+backward a single XLA program (jax.vjp of the scan), where
the reference needs a C++ grad-op per timestep. Gate semantics match the
reference exactly (LSTM chunks [i, f, g, o] rnn.py:518-537; GRU
``h = (h_prev - c) * z + c`` rnn.py:665-686) so state dicts are numerically
interchangeable.

Variable-length sequences use the dense-plus-lengths design (no LoDTensor —
SURVEY.md §7): `sequence_length` masks state updates inside the scan, so
final states equal the last valid step and padded outputs are zeroed.
"""
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.tensor import Tensor
from ..ops.dispatch import def_op
from . import initializer as I
from .layer import Layer, LayerList


# --------------------------------------------------------------------------- #
# fused single-(layer,direction) sequence kernels                             #
# --------------------------------------------------------------------------- #

def _mask_carry(new, old, valid):
    return jnp.where(valid[:, None], new, old)


def _scan_rnn(step, x_proj, init, w_hh, b_hh, lengths, reverse):
    """Run `step` over time-major projected inputs with optional length mask.

    x_proj: [T, B, G*H] (input projection already added, biases included).
    init:   tuple of [B, H] carries.
    Returns (outputs [T, B, H], final carries).
    """
    T = x_proj.shape[0]
    ts = jnp.arange(T)
    if reverse:
        x_proj = jnp.flip(x_proj, axis=0)
        ts = jnp.flip(ts, axis=0)

    def body(carry, inp):
        xt, t = inp
        new_carry, out = step(carry, xt, w_hh, b_hh)
        if lengths is not None:
            valid = t < lengths            # [B]
            new_carry = tuple(_mask_carry(n, o, valid)
                              for n, o in zip(new_carry, carry))
            out = jnp.where(valid[:, None], out, jnp.zeros_like(out))
        return new_carry, out

    final, ys = lax.scan(body, init, (x_proj, ts))
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, final


def _simple_step(act):
    def step(carry, xt, w_hh, b_hh):
        (h,) = carry
        pre = xt + h @ w_hh.T + b_hh
        h = jnp.tanh(pre) if act == "tanh" else jax.nn.relu(pre)
        return (h,), h
    return step


def _lstm_step(carry, xt, w_hh, b_hh):
    h, c = carry
    gates = xt + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    c = f * c + i * jnp.tanh(g)
    h = o * jnp.tanh(c)
    return (h, c), h


def _gru_step(carry, xt, w_hh, b_hh):
    (h,) = carry
    # reset gate applies AFTER the recurrent matmul (ref rnn.py:683)
    hg = h @ w_hh.T + b_hh
    x_r, x_z, x_c = jnp.split(xt, 3, axis=-1)
    h_r, h_z, h_c = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(x_r + h_r)
    z = jax.nn.sigmoid(x_z + h_z)
    c = jnp.tanh(x_c + r * h_c)
    h = (h - c) * z + c
    return (h,), h


@def_op("simple_rnn_seq", n_tensor_args=7)
def simple_rnn_seq(x, h0, w_ih, w_hh, b_ih, b_hh, lengths,
                   activation="tanh", reverse=False):
    """One SimpleRNN layer over a full [T, B, I] time-major sequence."""
    xp = x @ w_ih.T + b_ih
    ys, (h,) = _scan_rnn(_simple_step(activation), xp, (h0,), w_hh, b_hh,
                         lengths, reverse)
    return ys, h


@def_op("lstm_seq", n_tensor_args=8)
def lstm_seq(x, h0, c0, w_ih, w_hh, b_ih, b_hh, lengths, reverse=False):
    """One LSTM layer over a full [T, B, I] time-major sequence."""
    xp = x @ w_ih.T + b_ih
    ys, (h, c) = _scan_rnn(_lstm_step, xp, (h0, c0), w_hh, b_hh,
                           lengths, reverse)
    return ys, h, c


@def_op("gru_seq", n_tensor_args=7)
def gru_seq(x, h0, w_ih, w_hh, b_ih, b_hh, lengths, reverse=False):
    """One GRU layer over a full [T, B, I] time-major sequence."""
    xp = x @ w_ih.T + b_ih
    ys, (h,) = _scan_rnn(_gru_step, xp, (h0,), w_hh, b_hh, lengths, reverse)
    return ys, h


# --------------------------------------------------------------------------- #
# cells                                                                       #
# --------------------------------------------------------------------------- #

class RNNCellBase(Layer):
    """ref python/paddle/nn/layer/rnn.py:144."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        dtype = dtype or "float32"
        if isinstance(shape[0], (list, tuple)):
            return tuple(
                Tensor(jnp.full((batch,) + tuple(s), init_value,
                                dtype=jnp.dtype(dtype)))
                for s in shape)
        return Tensor(jnp.full((batch,) + tuple(shape), init_value,
                               dtype=jnp.dtype(dtype)))

    def _make_weights(self, input_size, hidden_size, n_gates,
                      weight_ih_attr, weight_hh_attr, bias_ih_attr,
                      bias_hh_attr):
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        g = n_gates * hidden_size
        self.weight_ih = self.create_parameter(
            (g, input_size), weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter(
            (g, hidden_size), weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter(
            (g,), bias_ih_attr, is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter(
            (g,), bias_hh_attr, is_bias=True, default_initializer=u)


class SimpleRNNCell(RNNCellBase):
    """Elman RNN cell (ref rnn.py:268)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError(
                f"activation for SimpleRNNCell should be tanh or relu, "
                f"but got {activation}")
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        self._make_weights(input_size, hidden_size, 1, weight_ih_attr,
                           weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        from . import functional as F
        if states is None:
            states = self.get_initial_states(inputs)
        pre = (F.linear(inputs, self.weight_ih.T, self.bias_ih)
               + F.linear(states, self.weight_hh.T, self.bias_hh))
        h = pre.tanh() if self.activation == "tanh" else F.relu(pre)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class LSTMCell(RNNCellBase):
    """LSTM cell, gate chunks [i, f, g, o] (ref rnn.py:400,518-537)."""

    def __init__(self, input_size, hidden_size,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self._make_weights(input_size, hidden_size, 4, weight_ih_attr,
                           weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states
        x = inputs if isinstance(inputs, Tensor) else Tensor(inputs)
        ys, h, c = lstm_seq(x.unsqueeze(0), h0, c0, self.weight_ih,
                            self.weight_hh, self.bias_ih, self.bias_hh, None)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class GRUCell(RNNCellBase):
    """GRU cell, h = (h_prev - c) * z + c (ref rnn.py:553,665-686)."""

    def __init__(self, input_size, hidden_size,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self._make_weights(input_size, hidden_size, 3, weight_ih_attr,
                           weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        x = inputs if isinstance(inputs, Tensor) else Tensor(inputs)
        ys, h = gru_seq(x.unsqueeze(0), states, self.weight_ih,
                        self.weight_hh, self.bias_ih, self.bias_hh, None)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


# --------------------------------------------------------------------------- #
# wrappers                                                                    #
# --------------------------------------------------------------------------- #

_FUSED = {}  # cell class -> runner; filled below


def _as_tuple(states):
    return states if isinstance(states, (tuple, list)) else (states,)


class RNN(Layer):
    """Run a cell over a sequence (ref rnn.py:700).

    Known cells (SimpleRNNCell/LSTMCell/GRUCell) take the fused-scan fast
    path; custom cells fall back to a per-step python loop (eager autograd
    still works; wrap the whole step in jit.to_static for speed).
    """

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        runner = _FUSED.get(type(self.cell))
        if runner is not None:
            outs, final = runner(self.cell, x, initial_states,
                                 sequence_length, self.is_reverse)
        else:
            outs, final = self._loop(x, initial_states, sequence_length)
        if not self.time_major:
            outs = outs.transpose([1, 0, 2])
        return outs, final

    def _loop(self, x, initial_states, sequence_length):
        from ..ops import manipulation as M
        T = x.shape[0]
        states = initial_states
        if states is None:
            states = self.cell.get_initial_states(x, batch_dim_idx=1)
        order = range(T - 1, -1, -1) if self.is_reverse else range(T)
        outs = [None] * T
        for t in order:
            out, new_states = self.cell(x[t], states)
            if sequence_length is not None:
                valid = Tensor((t < sequence_length._data)[:, None])
                zero = Tensor(jnp.zeros_like(out._data))
                outs[t] = M.where(valid, out, zero)
                # hold states past each sequence's end (matches fused path)
                new_flat = _as_tuple(new_states)
                old_flat = _as_tuple(states)
                held = tuple(M.where(valid, n, o)
                             for n, o in zip(new_flat, old_flat))
                states = held if isinstance(new_states, (tuple, list)) \
                    else held[0]
            else:
                outs[t] = out
                states = new_states
        return M.stack(outs, axis=0), states


def _run_simple(cell, x, init, lengths, reverse):
    h0 = _as_tuple(init)[0] if init is not None else \
        cell.get_initial_states(x, batch_dim_idx=1)
    ys, h = simple_rnn_seq(x, h0, cell.weight_ih, cell.weight_hh,
                           cell.bias_ih, cell.bias_hh, lengths,
                           activation=cell.activation, reverse=reverse)
    return ys, h


def _run_lstm(cell, x, init, lengths, reverse):
    if init is None:
        init = cell.get_initial_states(x, batch_dim_idx=1)
    h0, c0 = init
    ys, h, c = lstm_seq(x, h0, c0, cell.weight_ih, cell.weight_hh,
                        cell.bias_ih, cell.bias_hh, lengths, reverse=reverse)
    return ys, (h, c)


def _run_gru(cell, x, init, lengths, reverse):
    h0 = _as_tuple(init)[0] if init is not None else \
        cell.get_initial_states(x, batch_dim_idx=1)
    ys, h = gru_seq(x, h0, cell.weight_ih, cell.weight_hh,
                    cell.bias_ih, cell.bias_hh, lengths, reverse=reverse)
    return ys, h


_FUSED[SimpleRNNCell] = _run_simple
_FUSED[LSTMCell] = _run_lstm
_FUSED[GRUCell] = _run_gru


class BiRNN(Layer):
    """Forward + backward cells over the same sequence (ref rnn.py:775)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops import manipulation as M
        if initial_states is None:
            states_fw = states_bw = None
        else:
            states_fw, states_bw = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw, sequence_length)
        return M.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) stack (ref rnn.py:854).

    Parameter naming follows the reference flat form: weight_ih_l{k} /
    weight_hh_l{k} / bias_ih_l{k} / bias_hh_l{k} with `_reverse` suffix for
    the backward direction, so state dicts port over directly.
    """

    MODES = {"RNN_TANH": (1, "simple"), "RNN_RELU": (1, "simple"),
             "LSTM": (4, "lstm"), "GRU": (3, "gru")}

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if direction in ("bidirect", "bidirectional"):
            self.num_directions = 2
        elif direction == "forward":
            self.num_directions = 1
        else:
            raise ValueError(
                f"direction should be forward or bidirect(ional), "
                f"got {direction}")
        n_gates, self._kind = self.MODES[mode]
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(self.num_directions):
                isize = input_size if layer == 0 \
                    else hidden_size * self.num_directions
                sfx = f"l{layer}" + ("_reverse" if d == 1 else "")
                g = n_gates * hidden_size
                setattr(self, f"weight_ih_{sfx}", self.create_parameter(
                    (g, isize), weight_ih_attr, default_initializer=u))
                setattr(self, f"weight_hh_{sfx}", self.create_parameter(
                    (g, hidden_size), weight_hh_attr, default_initializer=u))
                setattr(self, f"bias_ih_{sfx}", self.create_parameter(
                    (g,), bias_ih_attr, is_bias=True, default_initializer=u))
                setattr(self, f"bias_hh_{sfx}", self.create_parameter(
                    (g,), bias_hh_attr, is_bias=True, default_initializer=u))

    def _weights(self, layer, d):
        sfx = f"l{layer}" + ("_reverse" if d == 1 else "")
        return (getattr(self, f"weight_ih_{sfx}"),
                getattr(self, f"weight_hh_{sfx}"),
                getattr(self, f"bias_ih_{sfx}"),
                getattr(self, f"bias_hh_{sfx}"))

    def _zeros(self, x):
        batch = x.shape[1]
        n = self.num_layers * self.num_directions
        return jnp.zeros((n, batch, self.hidden_size), dtype=x._data.dtype)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from . import functional as F
        from ..ops import manipulation as M
        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        lengths = sequence_length
        is_lstm = self._kind == "lstm"

        if initial_states is None:
            z = Tensor(self._zeros(x))
            initial_states = (z, z.clone()) if is_lstm else z
        if is_lstm:
            h_all, c_all = initial_states
        else:
            h_all = initial_states

        out = x
        hs, cs = [], []
        for layer in range(self.num_layers):
            per_dir = []
            for d in range(self.num_directions):
                idx = layer * self.num_directions + d
                w_ih, w_hh, b_ih, b_hh = self._weights(layer, d)
                h0 = h_all[idx]
                if self._kind == "simple":
                    act = "tanh" if self.mode == "RNN_TANH" else "relu"
                    ys, h = simple_rnn_seq(out, h0, w_ih, w_hh, b_ih, b_hh,
                                           lengths, activation=act,
                                           reverse=bool(d))
                    hs.append(h)
                elif self._kind == "gru":
                    ys, h = gru_seq(out, h0, w_ih, w_hh, b_ih, b_hh,
                                    lengths, reverse=bool(d))
                    hs.append(h)
                else:
                    c0 = c_all[idx]
                    ys, h, c = lstm_seq(out, h0, c0, w_ih, w_hh, b_ih, b_hh,
                                        lengths, reverse=bool(d))
                    hs.append(h)
                    cs.append(c)
                per_dir.append(ys)
            out = per_dir[0] if len(per_dir) == 1 \
                else M.concat(per_dir, axis=-1)
            if self.dropout > 0.0 and layer < self.num_layers - 1:
                out = F.dropout(out, p=self.dropout,
                                training=self.training)
        final_h = M.stack(hs, axis=0)
        if not self.time_major:
            out = out.transpose([1, 0, 2])
        if is_lstm:
            return out, (final_h, M.stack(cs, axis=0))
        return out, final_h

    def extra_repr(self):
        return (f"{self.input_size}, {self.hidden_size}, "
                f"num_layers={self.num_layers}, mode={self.mode}")


class SimpleRNN(_RNNBase):
    """ref rnn.py:1090."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    """ref rnn.py:1197."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    """ref rnn.py:1308."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


# public alias (ref nn/layer/rnn.py RNNBase)
RNNBase = _RNNBase


# --------------------------------------------------------------------------- #
# fluid-era cell-step ops (ref operators/gru_unit_op.cc, lstm_unit_op.cc,    #
# lstmp_op.cc) — single-step / projected variants registered as ops so      #
# 1.x-style unrolled RNN programs serialize to the desc                      #
# --------------------------------------------------------------------------- #

@def_op("gru_unit", n_tensor_args=4)
def gru_unit(x_gates, hidden_prev, weight, bias,
             gate_activation="sigmoid", activation="tanh",
             origin_mode=False):
    """One GRU step, fluid layout (ref operators/gru_unit_op.cc):
    x_gates: [B, 3D] (input already projected), hidden_prev: [B, D],
    weight: [D, 3D] stored flat — the reference kernel (gru_unit_op.h
    GEMMs: ldb=2*frame_size over the first 2*D*D elements, then
    ldb=frame_size from offset 2*D*D) reads it as a packed [D, 2D]
    update/reset block followed by a [D, D] candidate block, NOT as
    column slices of a [D, 3D] matrix; bias: [1, 3D]. Returns
    (gate [B,3D], reset_hidden_prev [B,D], hidden [B,D]) like the ref op."""
    d = hidden_prev.shape[1]
    g = x_gates + bias
    wf = weight.reshape(-1)
    w_rz = wf[:2 * d * d].reshape(d, 2 * d)
    w_c = wf[2 * d * d:].reshape(d, d)
    rz = g[:, :2 * d] + hidden_prev @ w_rz
    act = jax.nn.sigmoid if gate_activation == "sigmoid" else jnp.tanh
    u = act(rz[:, :d])
    r = act(rz[:, d:])
    rhp = r * hidden_prev
    c_in = g[:, 2 * d:] + rhp @ w_c
    cact = jnp.tanh if activation == "tanh" else jax.nn.sigmoid
    c = cact(c_in)
    if origin_mode:
        h = u * hidden_prev + (1.0 - u) * c
    else:
        h = (1.0 - u) * hidden_prev + u * c
    gate_out = jnp.concatenate([u, r, c], axis=1)
    return gate_out, rhp, h


@def_op("lstm_unit", n_tensor_args=2)
def lstm_unit(x_gates, c_prev, forget_bias=0.0):
    """One LSTM step on pre-projected gates (ref operators/lstm_unit_op.cc):
    x_gates: [B, 4D] in (i, g, f, o) order like the reference kernel,
    c_prev: [B, D]. Returns (c, h)."""
    d = c_prev.shape[1]
    i = jax.nn.sigmoid(x_gates[:, :d])
    g = jnp.tanh(x_gates[:, d:2 * d])
    f = jax.nn.sigmoid(x_gates[:, 2 * d:3 * d] + forget_bias)
    o = jax.nn.sigmoid(x_gates[:, 3 * d:])
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return c, h


@def_op("lstmp_seq", n_tensor_args=9)
def lstmp_seq(x, h0, c0, w_ih, w_hh, b_ih, b_hh, w_proj, lengths,
              reverse=False):
    """LSTM with recurrent projection (ref operators/lstmp_op.cc): the
    recurrent state fed back is r_t = h_t @ w_proj, so w_hh is [4H, P].
    x: [T, B, I]; returns (ys [T, B, P], r_T, c_T). Like the other seq
    kernels here, padding steps freeze the carry (live mask per timestep),
    so rT/cT are the states at each row's last valid step and reverse=True
    consumes timesteps from each row's true region."""
    T = x.shape[0]
    xp = x @ w_ih.T + b_ih
    ts = jnp.arange(T)
    if reverse:
        xp = jnp.flip(xp, axis=0)
        ts = jnp.flip(ts, axis=0)

    def step(carry, inp):
        xt, t = inp
        r, c = carry
        gates = xt + r @ w_hh.T + b_hh
        d = c.shape[1]
        i = jax.nn.sigmoid(gates[:, :d])
        f = jax.nn.sigmoid(gates[:, d:2 * d])
        g = jnp.tanh(gates[:, 2 * d:3 * d])
        o = jax.nn.sigmoid(gates[:, 3 * d:])
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        r2 = h2 @ w_proj
        if lengths is not None:
            valid = (t < lengths)[:, None]
            r2 = jnp.where(valid, r2, r)
            c2 = jnp.where(valid, c2, c)
        out = r2 if lengths is None else jnp.where(
            (t < lengths)[:, None], r2, jnp.zeros_like(r2))
        return (r2, c2), out

    (rT, cT), ys = jax.lax.scan(step, (h0, c0), (xp, ts))
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, rT, cT
