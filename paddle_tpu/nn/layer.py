"""nn.Layer — module base class (ref python/paddle/fluid/dygraph/layers.py:76).

Keeps the reference surface (sublayers/parameters/state_dict/hooks/train-eval,
create_parameter with initializer attrs) while staying functional-transform
friendly: `functional_state` / `functional_call` expose the layer as a pure
function of (params, buffers, inputs) so jax.jit/grad/shard_map can consume it
(the performance path; see jit/compile.py).
"""
import collections
import contextlib

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import state
from ..framework.tensor import Tensor, Parameter
from . import initializer as I


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks, self._key = hooks, key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ------------------------------------------------------------ registration
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning params")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning layers")
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                del params[name]
            if layers is not None and name in layers:
                del layers[name]
            if buffers is not None and name in buffers:
                if isinstance(value, Tensor):
                    buffers[name] = value
                    return
                del buffers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name, parameter):
        self._parameters[str(name)] = parameter
        return parameter

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[str(name)] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(str(name))
        else:
            # static capture relies on this to thread the buffer through the
            # desc as a persist var instead of freezing it as a constant
            tensor.persistable = True
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """ref dygraph/layers.py create_parameter + ParamAttr handling."""
        from .param_attr import ParamAttr
        dtype = dtype or self._dtype or "float32"
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        elif is_bias:
            init = I.Constant(0.0)
        else:
            init = I.XavierNormal()
        data = init(shape, dtype)
        p = Parameter(data, name=(attr.name if attr else None),
                      trainable=(attr.trainable if attr else True))
        if attr is not None:
            p.regularizer = attr.regularizer
            p.learning_rate = attr.learning_rate
        else:
            p.regularizer = None
            p.learning_rate = 1.0
        return p

    def create_tensor(self, name=None, persistable=False, dtype=None):
        return Tensor(jnp.zeros([], state.get_default_dtype()))

    # ------------------------------------------------------------ iteration
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(prefix=sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(prefix=sub_prefix)

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for layer in self._sub_layers.values():
            if layer is not None:
                out.extend(layer.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True)

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # ------------------------------------------------------------ modes
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ------------------------------------------------------------ hooks
    def register_forward_pre_hook(self, hook):
        key = len(self._forward_pre_hooks)
        self._forward_pre_hooks[key] = hook
        return HookRemoveHelper(self._forward_pre_hooks, key)

    def register_forward_post_hook(self, hook):
        key = len(self._forward_post_hooks)
        self._forward_post_hooks[key] = hook
        return HookRemoveHelper(self._forward_post_hooks, key)

    # ------------------------------------------------------------ call
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    # ------------------------------------------------------------ state dict
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix=""):
        dest = destination if destination is not None else collections.OrderedDict()
        for n, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[n] = p
        for n, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            leaf = n.rsplit(".", 1)[-1]
            if leaf not in self._non_persistable_buffer_names:
                dest[n] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                own[k].set_value(arr.astype(own[k].numpy().dtype))
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            for p in self.parameters():
                p._data = p._data.astype(dtype)
            for b in self.buffers():
                from ..framework.dtype import is_floating_point
                if is_floating_point(b.dtype):
                    b._data = b._data.astype(dtype)
        return self

    def float(self):
        return self.to(dtype=jnp.float32)

    def bfloat16(self):
        return self.to(dtype=jnp.bfloat16)

    # ------------------------------------------------------------ functional
    def functional_state(self):
        """(params, buffers) as flat name->jnp.ndarray dicts, for jit'd steps."""
        params = {n: p._data for n, p in self.named_parameters()}
        buffers = {n: b._data for n, b in self.named_buffers()}
        return params, buffers

    @contextlib.contextmanager
    def _use_state(self, params=None, buffers=None):
        """Temporarily swap parameter/buffer arrays (used while tracing)."""
        saved_p, saved_b = {}, {}
        named_p = dict(self.named_parameters())
        named_b = dict(self.named_buffers())
        try:
            if params is not None:
                for n, arr in params.items():
                    saved_p[n] = named_p[n]._data
                    named_p[n]._data = arr
            if buffers is not None:
                for n, arr in buffers.items():
                    saved_b[n] = named_b[n]._data
                    named_b[n]._data = arr
            yield named_p, named_b
        finally:
            for n, arr in saved_p.items():
                named_p[n]._data = arr
            for n, arr in saved_b.items():
                named_b[n]._data = arr

    def functional_call(self, params, buffers, *inputs, method=None,
                        **kwargs):
        """Pure call: returns (outputs, new_buffers). Safe under jax tracing.
        `method` selects a non-forward entry point (e.g. GPT decode_step);
        only array-like positionals are Tensor-wrapped — pytrees (KV caches)
        and scalars pass through untouched."""
        def wrap(i):
            if isinstance(i, Tensor):
                return i
            if isinstance(i, (jax.Array, jax.core.Tracer, np.ndarray)):
                return Tensor(i)
            return i

        with state.functional_mode_ctx():
            with self._use_state(params, buffers) as (named_p, named_b):
                wrapped = [wrap(i) for i in inputs]
                for n in params:
                    named_p[n].stop_gradient = False
                fn = getattr(self, method) if method else self
                out = fn(*wrapped, **kwargs)
                new_buffers = {n: named_b[n]._data for n in (buffers or {})}
        return out, new_buffers

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            rep = [rep[0]] + ["  " + r for r in rep[1:]]
            lines.append(f"  ({name}): " + "\n".join(rep))
        main = self.__class__.__name__ + "(" + extra
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def extra_repr(self):
        return ""


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __len__(self):
        return len(self._sub_layers)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        if idx < 0:
            idx += len(self)
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                len(layers[0]) and isinstance(layers[0][0], (list, tuple)):
            for name, l in layers[0]:
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, tuple):
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __len__(self):
        return len(self._parameters)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, p):
        self.add_parameter(str(len(self)), p)
        return self
