"""Conv layers (ref python/paddle/nn/layer/conv.py)."""
from . import functional as F
from . import initializer as I
from .layer import Layer


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, ndim,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NCHW", transpose=False, output_padding=0):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * ndim
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._transpose = transpose
        self._output_padding = output_padding
        if transpose:
            w_shape = [in_channels, out_channels // groups, *kernel_size]
        else:
            w_shape = [out_channels, in_channels // groups, *kernel_size]
        fan_in = (in_channels // groups) * int(
            __import__("numpy").prod(kernel_size))
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={list(self._kernel_size)}, stride={self._stride}"
                + (f", padding={self._padding}" if self._padding else ""))


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, stride=self._stride,
                        padding=self._padding, dilation=self._dilation,
                        groups=self._groups, data_format=self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, stride=self._stride,
                        padding=self._padding, dilation=self._dilation,
                        groups=self._groups, data_format=self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, stride=self._stride,
                        padding=self._padding, dilation=self._dilation,
                        groups=self._groups, data_format=self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(
            x, self.weight, self.bias, stride=self._stride,
            padding=self._padding, output_padding=self._output_padding,
            dilation=self._dilation, groups=self._groups,
            output_size=output_size, data_format=self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        x4 = x.unsqueeze(-1)
        w = self.weight.unsqueeze(-1)  # grad flows back through the unsqueeze node
        out = F.conv2d_transpose(
            x4, w, self.bias,
            stride=(self._stride if isinstance(self._stride, int)
                    else self._stride[0], 1),
            padding=(self._padding if isinstance(self._padding, int)
                     else self._padding[0], 0),
            output_padding=(self._output_padding, 0),
            dilation=(self._dilation if isinstance(self._dilation, int)
                      else self._dilation[0], 1),
            groups=self._groups)
        return out.squeeze(-1)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(
            x, self.weight, self.bias, stride=self._stride,
            padding=self._padding, output_padding=self._output_padding,
            dilation=self._dilation, groups=self._groups)
