"""Transformer stack (ref python/paddle/nn/layer/transformer.py:115-1094:
MultiHeadAttention, TransformerEncoder/DecoderLayer, TransformerEncoder/Decoder,
Transformer).

TPU-first: the attention core is scaled_dot_product_attention (below), which
routes to the Pallas flash-attention kernel when eligible (ops/pallas/) and
otherwise to an XLA-fused softmax(QK^T)V, in layout [batch, heads, seq, head_dim].
"""
import collections

import numpy as np
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import apply
from . import functional as F
from .layer import Layer, LayerList
from .layers_common import Linear, Dropout
from .norm import LayerNorm


def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 training=True, causal=False, scale=None):
    """q,k,v: [B, H, S, D]. Routes to pallas flash attention on TPU when
    shapes allow; XLA path otherwise."""
    from ..ops.pallas import flash_attention
    return flash_attention(q, k, v, attn_mask=attn_mask, causal=causal,
                           dropout_p=dropout_p if training else 0.0,
                           scale=scale)


class MultiHeadAttention(Layer):
    """ref transformer.py:115. Weight layouts match the reference's Linear
    projections (q/k/v/out proj over embed_dim)."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None,
                 attn_layout=None):
        super().__init__()
        import os as _os
        # "bshd" (default): the flash kernel reads [B,S,H,D] straight
        # off the projections — no layout transposes (same knob as
        # GPTConfig.attn_layout, measured faster on-chip for both GPT
        # and BERT topologies; PT_ATTN_LAYOUT lets benches A/B it)
        self.attn_layout = (attn_layout
                            or _os.environ.get("PT_ATTN_LAYOUT", "bshd"))
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim

        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _reshape_heads(self, x):
        # [B, S, E] -> [B, H, S, D]
        b, s = x.shape[0], x.shape[1]
        return x.reshape([b, s, self.num_heads, self.head_dim]) \
                .transpose([0, 2, 1, 3])

    def gen_cache(self, key, value=None, type=Cache):
        if type == MultiHeadAttention.StaticCache:
            k = self._reshape_heads(self.k_proj(key))
            v = self._reshape_heads(self.v_proj(value if value is not None
                                                else key))
            return self.StaticCache(k, v)
        if value is None:
            # incremental decode cache seeded empty
            import paddle_tpu as pt
            b = key.shape[0]
            k = pt.zeros([b, self.num_heads, 0, self.head_dim])
            v = pt.zeros([b, self.num_heads, 0, self.head_dim])
            return self.Cache(k, v)
        return self.Cache(key, value)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        if (self.attn_layout == "bshd" and cache is None
                and not self.need_weights and attn_mask is None):
            # transpose-free path: [B,S,E] -> [B,S,H,D] views feed the
            # packed-lane flash kernel natively
            from ..ops.pallas.flash_attention import flash_attention
            b, s = query.shape[0], query.shape[1]
            hd = (self.num_heads, self.head_dim)
            q = self.q_proj(query).reshape([b, s, *hd])
            k = self.k_proj(key).reshape([b, key.shape[1], *hd])
            v = self.v_proj(value).reshape([b, value.shape[1], *hd])
            out = flash_attention(
                q, k, v, causal=False,
                dropout_p=self.dropout if self.training else 0.0,
                layout="bshd")
            out = out.reshape([b, s, self.embed_dim])
            return self.out_proj(out)
        q = self._reshape_heads(self.q_proj(query))
        if isinstance(cache, MultiHeadAttention.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._reshape_heads(self.k_proj(key))
            v = self._reshape_heads(self.v_proj(value))
            if isinstance(cache, MultiHeadAttention.Cache):
                from ..ops.manipulation import concat
                k = concat([cache.k, k], axis=2)
                v = concat([cache.v, v], axis=2)
                cache = self.Cache(k, v)

        weights = None
        if self.need_weights:
            # weights require materialising S x S — use the explicit path
            from ..ops.dispatch import apply
            import math as _math
            d = q.shape[-1]
            sc = 1.0 / _math.sqrt(d)

            def attn_w(q_, k_):
                import jax
                logits = jnp.einsum("bhqd,bhkd->bhqk", q_, k_,
                                    preferred_element_type=jnp.float32) * sc
                return jax.nn.softmax(logits, axis=-1)
            weights = apply(attn_w, (q, k), name="attn_weights")
        out = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training)
        # [B, H, S, D] -> [B, S, E]
        b, s = out.shape[0], out.shape[2]
        out = out.transpose([0, 2, 1, 3]).reshape([b, s, self.embed_dim])
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(weights)
        if cache is not None and isinstance(cache, MultiHeadAttention.Cache):
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)


class TransformerEncoderLayer(Layer):
    """ref transformer.py TransformerEncoderLayer (act_dropout, normalize_before)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self._config = dict(
            d_model=d_model, nhead=nhead, dim_feedforward=dim_feedforward,
            dropout=dropout, activation=activation, attn_dropout=attn_dropout,
            act_dropout=act_dropout, normalize_before=normalize_before,
            weight_attr=weight_attr, bias_attr=bias_attr)
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, incremental_cache = self.self_attn(src, src, src, src_mask,
                                                    cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        # replicate with fresh params (ref _get_clones deep-copies; rebuild
        # from config to get independent initialisations)
        self.layers = LayerList([encoder_layer] + [
            _clone_layer(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask=src_mask)
            else:
                output, new_cache = mod(output, src_mask=src_mask,
                                        cache=cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    """ref transformer.py TransformerDecoderLayer: self-attn + cross-attn + FFN."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self._config = dict(
            d_model=d_model, nhead=nhead, dim_feedforward=dim_feedforward,
            dropout=dropout, activation=activation, attn_dropout=attn_dropout,
            act_dropout=act_dropout, normalize_before=normalize_before,
            weight_attr=weight_attr, bias_attr=bias_attr)
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
            if isinstance(tgt, tuple):
                tgt = tgt[0]
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache, cache[1]))

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(memory, memory,
                                           MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] + [
            _clone_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask=tgt_mask,
                             memory_mask=memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask=tgt_mask,
                                        memory_mask=memory_mask, cache=cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    """ref transformer.py:886 full encoder-decoder Transformer."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    def generate_square_subsequent_mask(self, length):
        import paddle_tpu as pt
        mask = pt.triu(pt.full([length, length], float("-inf")), diagonal=1)
        return mask


def _clone_layer(layer):
    """Fresh layer with the same config but independent initialisation
    (the reference rebuilds per-layer from config, transformer.py ~_config)."""
    return type(layer)(**layer._config)


def cached_decode_attention(q, ck, cv, pos, scale, window=None,
                            sanitize=False):
    """Single-token cached attention core shared by the GPT and LLaMA
    decoders. q: [B, H, 1, D]; ck/cv: [B, Hkv, L, D] with H % Hkv == 0 —
    grouped (GQA) when H > Hkv, WITHOUT materialising the repeated cache:
    q is reshaped to [B, Hkv, rep, D] and contracted against the
    un-repeated KV buffers. window=W restricts to the last W cache
    positions (sliding-window decode matching the training band).
    `pos` is a traced scalar (lockstep batch) or a [B] vector — the
    slot-wise serving case where every row sits at its own depth; the
    causal mask broadcasts per-row. Returns [B, H, 1, D] in cv.dtype.
    sanitize=True additionally zeroes V rows no query attends — needed
    when the cache view contains scratch-block garbage that may be
    non-finite (the paged reference path); the dense path skips the
    extra elementwise pass over the cache."""
    import jax
    import jax.numpy as jnp

    b, h, _, d = q.shape
    hkv, L = ck.shape[1], ck.shape[2]
    rep = h // hkv
    qf = q.astype(jnp.float32).reshape(b, hkv, rep, d)
    scores = jnp.einsum("bkrd,bkld->bkrl", qf,
                        ck.astype(jnp.float32)) * scale
    if jnp.ndim(pos):
        pos = jnp.reshape(pos, (b, 1, 1, 1))
    ks = jnp.arange(L)[None, None, None, :]
    mask = ks <= pos
    if window is not None:
        mask = mask & (ks > pos - window)
    probs = _masked_softmax(scores, mask).astype(cv.dtype)
    if sanitize:
        cv = _sanitize_unattended(cv, mask[:, 0, 0, :, None])
    out = jnp.einsum("bkrl,bkld->bkrd", probs, cv)
    return out.reshape(b, h, 1, d)


def _masked_softmax(scores, mask):
    """Softmax with HARD exclusion of masked positions: -inf (not the
    old -1e9 additive sentinel) before the max/exp, and fully-masked
    rows (all-scratch lanes, padded chunk tails) renormalise to exactly
    0 through the guarded `where` instead of averaging over a uniform
    -1e9 row. Masked-position garbage — scratch blocks hold arbitrary
    bytes, possibly non-finite — therefore can never reach the serving
    engines' isfinite poison sentinel, while a non-finite value at an
    ATTENDED position still propagates (exp(nan) is nan). For any row
    with at least one unmasked position this is bitwise identical to
    softmax over the -1e9-masked scores: exp(-1e9 - m) and
    exp(-inf - m) both round to exactly 0.0 in f32 for finite m."""
    import jax.numpy as jnp
    scores = jnp.where(mask, scores, -jnp.inf)
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.exp(scores - jnp.where(jnp.isfinite(m), m, 0.0))
    denom = jnp.sum(e, axis=-1, keepdims=True)
    # the guard must key on == 0 (fully-masked), NOT > 0: a non-finite
    # denom from a genuine fault fails `> 0` and would silently zero
    # the row; `== 0` lets nan fall through to the division instead
    return jnp.where(denom == 0, 0.0, e / denom)


def _sanitize_unattended(cv, attended):
    """Zero the V rows NO query attends (attended: [B, L] broadcastable
    against cv [B, Hkv, L, D], any-reduced over the query axes by the
    caller). A 0-probability key with non-finite garbage would still
    produce 0 * nan == nan in the probs @ V contraction — scratch-block
    poison leaking past the mask. Keys attended by at least one query
    keep their value, so a GENUINE non-finite at an attended position
    propagates to that lane's logits (the poison sentinel) exactly as
    before; for finite caches this is bitwise a no-op (0 * v == 0 * 0)."""
    import jax.numpy as jnp
    b = attended.shape[0]
    return jnp.where(jnp.reshape(attended, (b, 1) + attended.shape[1:]),
                     cv, jnp.zeros((), cv.dtype))


def scatter_kv_at(cache, kv_t, pos):
    """Write the step's K or V [B, Hkv, 1, D] into cache [B, Hkv, L, D]
    at a per-row position vector pos [B] (slot-wise decode: each serving
    slot is at its own depth). vmap over the batch axis lowers to one
    scatter — no per-slot unrolling in the compiled program. The scalar
    lockstep path keeps using dynamic_update_slice_in_dim directly."""
    import jax
    return jax.vmap(
        lambda c, t, p: jax.lax.dynamic_update_slice_in_dim(
            c, t, p, axis=1))(cache, kv_t.astype(cache.dtype), pos)


# ---------------------------------------------------------------------------
# paged KV cache primitives (serving/paged: block-table memory manager)
# ---------------------------------------------------------------------------
# The pool is [num_blocks, Hkv, block_size, D]; a request's cache is the
# ordered sequence of pool blocks named by its block TABLE (int32 block
# ids, host-managed by serving.paged.BlockPool). All shapes below are
# static — table entries are VALUES, not shapes — so one compiled
# program serves every allocation pattern (compile-once). Block 0 is
# the scratch block: inactive/invalid lanes are redirected there, its
# contents are garbage by design and never read by a surviving lane
# (the ks <= pos mask and the active-lane `where` discard them).


def gather_block_kv(pool, tables):
    """Materialise per-row KV views from the block pool. pool:
    [NB, Hkv, BS, D]; tables: [B, nblk] int32 → [B, Hkv, nblk*BS, D],
    position p of row b living at pool[tables[b, p // BS], :, p % BS].
    One gather — the paged analog of reading the dense [B, Hkv, L, D]
    cache (same bytes streamed when nblk*BS == L)."""
    import jax.numpy as jnp
    g = pool[tables]                           # [B, nblk, Hkv, BS, D]
    b, nblk, hkv, bs, d = g.shape
    return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(b, hkv, nblk * bs, d)


def scatter_block_kv_at(pool, kv_t, tables, pos):
    """Write one step's K or V [B, Hkv, 1, D] through block tables
    [B, nblk] at per-row positions pos [B]: row b lands in
    pool[tables[b, pos[b] // BS], :, pos[b] % BS]. One scatter. Rows
    whose table entry is the scratch block (retired/starved lanes —
    the host rewrites their table rows) collide there harmlessly."""
    import jax.numpy as jnp
    bs = pool.shape[2]
    blk = jnp.take_along_axis(tables, (pos // bs)[:, None], axis=1)[:, 0]
    return pool.at[blk, :, pos % bs, :].set(
        kv_t[:, :, 0, :].astype(pool.dtype))


def scatter_block_kv_chunk(pool, kv_c, table, positions, valid_len):
    """Write a prefill chunk's K or V [1, Hkv, C, D] through one row's
    block table [1, nblk] at absolute positions [C] (= chunk_start + i).
    Positions at or past valid_len (the padded tail of the last chunk)
    are redirected to the scratch block."""
    import jax.numpy as jnp
    nblk, bs = table.shape[1], pool.shape[2]
    c = positions.shape[0]
    # clamp BEFORE the table gather (a padded tail can index past the
    # table); invalid lanes are then redirected to scratch regardless
    blk = table[0, jnp.minimum(positions // bs, nblk - 1)]
    blk = jnp.where(jnp.arange(c) < valid_len, blk, 0)
    kv = jnp.transpose(kv_c[0], (1, 0, 2))     # [C, Hkv, D]
    return pool.at[blk, :, positions % bs, :].set(kv.astype(pool.dtype))


def scatter_block_kv_chunk_batched(pool, kv_c, tables, start, valid_len):
    """Write a C-token chunk's K or V [S, Hkv, C, D] for EVERY lane
    through its block table [S, nblk] at absolute positions start[s] + i
    (start: [S] int). Per-lane positions at or past valid_len[s] ([S])
    are redirected to the scratch block — the speculative verify wave
    clamps its k+1-token span per slot this way (horizon, per-request
    spec_len). The single-lane prefill variant above is the C-chunk/
    one-slot case of this; here S lanes scatter in ONE op, which is the
    verify program's write shape (serving/paged speculative decoding).
    Distinct lanes write distinct blocks (frontier blocks are private by
    the COW guard), so the only colliding writes are the scratch
    redirects — garbage by design."""
    import jax.numpy as jnp
    nblk, bs = tables.shape[1], pool.shape[2]
    s, c = kv_c.shape[0], kv_c.shape[2]
    positions = start[:, None] + jnp.arange(c)[None, :]         # [S, C]
    # clamp BEFORE the table gather (a clamped span can index past the
    # table); invalid lanes/positions then redirect to scratch anyway
    blk = jnp.take_along_axis(tables,
                              jnp.minimum(positions // bs, nblk - 1),
                              axis=1)                           # [S, C]
    blk = jnp.where(jnp.arange(c)[None, :] < valid_len[:, None], blk, 0)
    kv = jnp.transpose(kv_c, (0, 2, 1, 3))              # [S, C, Hkv, D]
    return pool.at[blk, :, positions % bs, :].set(kv.astype(pool.dtype))


def chunk_attention(q, ck, cv, start, scale, window=None,
                    sanitize=False):
    """Prefill-chunk attention core: C queries at absolute positions
    start + i over an L-position KV view (the gathered paged cache,
    which already contains this chunk's own K/V). q: [B, H, C, D];
    ck/cv: [B, Hkv, L, D] with H % Hkv == 0 — grouped (GQA) without
    materialising the repeated cache, exactly like
    cached_decode_attention (C == 1 of this is that function). `start`
    is a traced scalar or a [B] vector; each query row masks
    ks <= start + i (banded to the last `window` keys when given), so a
    chunk mid-prefill attends to every previous chunk's cached
    positions plus its own causal prefix. Returns [B, H, C, D] in
    cv.dtype. sanitize as in cached_decode_attention (paged gathered
    views only)."""
    import jax
    import jax.numpy as jnp

    b, h, c, d = q.shape
    hkv, L = ck.shape[1], ck.shape[2]
    rep = h // hkv
    qf = q.astype(jnp.float32).reshape(b, hkv, rep, c, d)
    scores = jnp.einsum("bkrcd,bkld->bkrcl", qf,
                        ck.astype(jnp.float32)) * scale
    if jnp.ndim(start):
        start = jnp.reshape(start, (b, 1, 1, 1, 1))
    qpos = start + jnp.arange(c).reshape(1, 1, 1, c, 1)
    ks = jnp.arange(L).reshape(1, 1, 1, 1, L)
    mask = ks <= qpos
    if window is not None:
        mask = mask & (ks > qpos - window)
    probs = _masked_softmax(scores, mask).astype(cv.dtype)
    if sanitize:
        cv = _sanitize_unattended(
            cv, jnp.any(mask, axis=3)[:, 0, 0, :, None])
    out = jnp.einsum("bkrcl,bkld->bkrcd", probs, cv)
    return out.reshape(b, h, c, d)
