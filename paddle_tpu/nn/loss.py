"""Loss layers (ref python/paddle/nn/layer/loss.py)."""
from . import functional as F
from .layer import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               use_softmax=self.use_softmax)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, reduction=self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, reduction=self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, reduction=self.reduction,
                                delta=self.delta)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight, self.ignore_index = weight, ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, weight=self.weight,
                          ignore_index=self.ignore_index,
                          reduction=self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, weight=self.weight,
                                      reduction=self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, weight=self.weight, reduction=self.reduction,
            pos_weight=self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, reduction=self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, margin=self.margin,
                                     reduction=self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, margin=self.margin,
                                      reduction=self.reduction)


class CTCLoss(Layer):
    """ref paddle.nn.CTCLoss (warpctc): log_probs [T, B, C] raw logits,
    labels [B, Lmax] padded."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class HSigmoidLoss(Layer):
    """ref nn/layer/loss.py HSigmoidLoss: hierarchical sigmoid over the
    default complete binary tree (custom path tables unsupported)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError("HSigmoidLoss: custom trees "
                                      "unsupported")
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr)
        self.bias = (self.create_parameter([num_classes - 1, 1],
                                           attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias)
