"""nn.functional — activations, conv/pool, norm, losses, embedding, dropout
(ref python/paddle/nn/functional/* and the kernels in paddle/fluid/operators/:
activation_op.cc, conv_cudnn_op.cu, pool_op, batch_norm_op, layer_norm_op,
softmax_with_cross_entropy_op, dropout_op, lookup_table_v2_op).

Convs ride lax.conv_general_dilated (MXU path); XLA picks TPU-optimal layouts so
both NCHW (paddle default) and NHWC are accepted.
"""
import math
import numbers

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..framework import state
from ..framework.dtype import convert_dtype
from ..framework.tensor import Tensor
from ..ops.dispatch import apply, as_array, register_op

# ----------------------------------------------------------------- activations


def _unary(fn, name):
    register_op(name, fn)

    def op(x, name=None, _opname=name):
        return apply(fn, (x,), name=_opname)
    op.__name__ = name
    return op


relu = _unary(jax.nn.relu, "relu")
relu6 = _unary(jax.nn.relu6, "relu6")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
tanh = _unary(jnp.tanh, "tanh")
silu = _unary(jax.nn.silu, "silu")
swish = silu
mish = _unary(lambda a: a * jnp.tanh(jax.nn.softplus(a)), "mish")
hardswish = _unary(jax.nn.hard_swish, "hardswish")
hardsigmoid = _unary(lambda a: jnp.clip(a / 6.0 + 0.5, 0.0, 1.0), "hardsigmoid")
tanhshrink = _unary(lambda a: a - jnp.tanh(a), "tanhshrink")


def _gelu_raw(a, approximate=False):
    return jax.nn.gelu(a, approximate=approximate)


register_op("gelu", _gelu_raw)


def gelu(x, approximate=False, name=None):
    return apply(_gelu_raw, (x,), {"approximate": bool(approximate)},
                 name="gelu")


def _leaky_relu_raw(a, negative_slope=0.01):
    return jax.nn.leaky_relu(a, negative_slope)


register_op("leaky_relu", _leaky_relu_raw)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(_leaky_relu_raw, (x,),
                 {"negative_slope": float(negative_slope)}, name="leaky_relu")


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha), (x,), name="elu")


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha), (x,), name="celu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
                 (x,), name="selu")


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
        shape = [1] * a.ndim
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)
    return apply(f, (x, weight), name="prelu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda a: jnp.clip(a, min, max), (x,), name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), (x,),
                 name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold, 0.0)),
                 (x,), name="softshrink")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(lambda a: jnp.where(a * beta > threshold, a,
                                     jax.nn.softplus(a * beta) / beta),
                 (x,), name="softplus")


def softsign(x, name=None):
    return apply(lambda a: a / (1 + jnp.abs(a)), (x,), name="softsign")


def maxout(x, groups, axis=1, name=None):
    def f(a):
        c = a.shape[axis]
        new_shape = list(a.shape)
        new_shape[axis] = c // groups
        new_shape.insert(axis + 1, groups)
        return jnp.max(a.reshape(new_shape), axis=axis + 1)
    return apply(f, (x,), name="maxout")


def _softmax_raw(a, axis=-1, to_dtype=None):
    if to_dtype is not None:
        a = a.astype(convert_dtype(to_dtype))
    return jax.nn.softmax(a, axis=axis)


register_op("softmax", _softmax_raw)


def softmax(x, axis=-1, dtype=None, name=None):
    return apply(_softmax_raw, (x,),
                 {"axis": int(axis), "to_dtype": None if dtype is None else
                  str(np.dtype(convert_dtype(dtype)))}, name="softmax")


def _log_softmax_raw(a, axis=-1, to_dtype=None):
    if to_dtype is not None:
        a = a.astype(convert_dtype(to_dtype))
    return jax.nn.log_softmax(a, axis=axis)


register_op("log_softmax", _log_softmax_raw)


def log_softmax(x, axis=-1, dtype=None, name=None):
    return apply(_log_softmax_raw, (x,),
                 {"axis": int(axis), "to_dtype": None if dtype is None else
                  str(np.dtype(convert_dtype(dtype)))}, name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    g = -jnp.log(-jnp.log(
        jax.random.uniform(state.next_rng_key(), tuple(as_array(x).shape)) + 1e-20))

    def f(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y)
            onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis) \
                if hasattr(jnp, "put_along_axis") else \
                jax.nn.one_hot(jnp.squeeze(idx, axis), y.shape[axis], axis=axis)
            y = onehot + y - lax.stop_gradient(y)
        return y
    return apply(f, (x,), name="gumbel_softmax")


# ----------------------------------------------------------------- linear / emb

def _linear_raw(a, w, b=None):
    out = jnp.matmul(a, w)
    return out if b is None else out + b


register_op("linear", _linear_raw)


def linear(x, weight, bias=None, name=None):
    """paddle weight layout: [in_features, out_features] (ref nn/functional/common.py:1419)."""
    if bias is None:
        return apply(_linear_raw, (x, weight), name="linear")
    return apply(_linear_raw, (x, weight, bias), name="linear")


def _embedding_raw(idx, w, padding_idx=None):
    out = jnp.take(w, idx, axis=0)
    if padding_idx is not None:
        mask = (idx == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


register_op("embedding", _embedding_raw)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Device-side gather (TPU: embedding lookups stay on-chip; host-resident
    sparse tables are the PS path, see distributed/ps). sparse=True makes the
    EAGER backward produce a SelectedRows gradient on `weight` — O(batch*dim)
    instead of O(vocab*dim) (ref lookup_table_v2_op is_sparse grad; under
    jit, XLA's fused scatter-add already gives this, so the flag only
    changes the eager tape)."""
    if padding_idx is not None and padding_idx < 0:
        # paddle semantics: negative pad indexes from the end of the table
        padding_idx = int(as_array(weight).shape[0]) + int(padding_idx)
    if sparse and not state.is_functional_mode() and state.is_grad_enabled() \
            and isinstance(weight, Tensor) and not weight.stop_gradient \
            and weight._node is None:
        # leaf tables only: a non-leaf weight's producer holds a jax vjp
        # that cannot consume a SelectedRows cotangent
        return _sparse_embedding_eager(x, weight, padding_idx)
    return apply(_embedding_raw, (x, weight),
                 {"padding_idx": None if padding_idx is None
                  else int(padding_idx)}, name="embedding")


def _sparse_embedding_eager(x, weight, padding_idx):
    """Eager gather whose GradNode emits SelectedRows for the table."""
    from ..framework.tape import GradNode
    from ..framework.selected_rows import SelectedRows
    ids = as_array(x)
    w = as_array(weight)
    out = _embedding_raw(ids, w, padding_idx=padding_idx)
    height = int(w.shape[0])      # don't capture w: it pins a stale table

    def vjp(cot):
        flat_ids = ids.ravel()
        vals = cot.reshape((-1,) + cot.shape[ids.ndim:])
        if padding_idx is not None:
            vals = jnp.where((flat_ids == padding_idx)[..., None], 0.0, vals)
        return (jnp.zeros_like(ids),          # ids: int input, skipped
                SelectedRows(flat_ids, vals, height))

    res = Tensor(out, stop_gradient=False)
    node = GradNode(vjp=vjp,
                    inputs=[x if isinstance(x, Tensor) else None, weight],
                    n_outputs=1, out_shapes=(out.shape,),
                    out_dtypes=(out.dtype,), name="sparse_embedding")
    res._node = node
    res._slot = 0
    return res


def one_hot(x, num_classes, name=None):
    return apply(lambda i: jax.nn.one_hot(i, num_classes, dtype=jnp.float32),
                 (x,), differentiable=False, name="one_hot")


# ----------------------------------------------------------------- dropout

def _dropout_raw(v, key, p=0.5, axis=None, mode="upscale_in_train",
                 training=True):
    """rng-explicit dropout (ref operators/dropout_op.cc: seed attr + mask
    output; here the mask is derived from a key input so the static desc
    replays with fresh randomness per run)."""
    if not training or p == 0.0:
        return v
    shape = tuple(v.shape)
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        shape = tuple(s if i in axes else 1 for i, s in enumerate(v.shape))
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, v / (1.0 - p), 0.0)
    return jnp.where(keep, v, 0.0)


register_op("dropout", _dropout_raw)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    # same gating as apply(): in functional (jit-trace) mode the recorder is
    # inert and the eager fast path below is safe
    rec = None if state.is_functional_mode() else state.get_static_recorder()
    if (not training or p == 0.0) and rec is None:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = state.next_rng_key()
    if isinstance(axis, (list, tuple)):
        axis = [int(a) for a in axis]
    elif axis is not None:
        axis = int(axis)
    # "__rng__": True asks the recorder to salt this op so the Executor
    # re-derives the key input per run (dispatch strips dunder attrs before
    # calling the impl)
    return apply(_dropout_raw, (x, Tensor(key)),
                 {"p": float(p), "axis": axis, "mode": mode,
                  "training": bool(training), "__rng__": True},
                 name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    a_ = as_array(x)
    keep = jax.random.bernoulli(state.next_rng_key(), 1.0 - p, tuple(a_.shape))
    q = 1.0 - p
    coef_a = (q + alpha_p ** 2 * q * p) ** -0.5
    coef_b = -coef_a * alpha_p * p

    def f(v):
        return coef_a * jnp.where(keep, v, alpha_p) + coef_b
    return apply(f, (x,), name="alpha_dropout")


# ----------------------------------------------------------------- conv / pool

def _norm_tuple(v, n):
    if isinstance(v, numbers.Number):
        return (int(v),) * n
    v = tuple(int(i) for i in v)
    if len(v) == 1:
        return v * n
    return v


def _conv_padding(padding, n, strides, dilations, ksize):
    """paddle padding spec -> lax padding list. Supports int, list, 'SAME','VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, numbers.Number):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n:
        if isinstance(padding[0], (list, tuple)):
            return [tuple(p) for p in padding]
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:  # [before0, after0, before1, after1...]
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """weight layout: [out_c, in_c/groups, kh, kw] (paddle/ref conv_op.cc)."""
    n = 2
    strides = _norm_tuple(stride, n)
    dilations = _norm_tuple(dilation, n)
    dn_str = ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" \
        else ("NHWC", "OIHW", "NHWC")

    def f(a, w, *maybe_b):
        pad = _conv_padding(padding, n, strides, dilations, w.shape[2:])
        dn = lax.conv_dimension_numbers(a.shape, w.shape, dn_str)
        out = lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups)
        if maybe_b:
            b = maybe_b[0]
            if data_format == "NCHW":
                out = out + b.reshape(1, -1, 1, 1)
            else:
                out = out + b.reshape(1, 1, 1, -1)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(f, args, name="conv2d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    n = 1
    strides = _norm_tuple(stride, n)
    dilations = _norm_tuple(dilation, n)
    dn_str = ("NCH", "OIH", "NCH") if data_format == "NCL" else ("NHC", "OIH", "NHC")

    def f(a, w, *maybe_b):
        pad = _conv_padding(padding, n, strides, dilations, w.shape[2:])
        dn = lax.conv_dimension_numbers(a.shape, w.shape, dn_str)
        out = lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups)
        if maybe_b:
            shape = (1, -1, 1) if data_format == "NCL" else (1, 1, -1)
            out = out + maybe_b[0].reshape(shape)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(f, args, name="conv1d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    n = 3
    strides = _norm_tuple(stride, n)
    dilations = _norm_tuple(dilation, n)
    dn_str = ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW" \
        else ("NDHWC", "OIDHW", "NDHWC")

    def f(a, w, *maybe_b):
        pad = _conv_padding(padding, n, strides, dilations, w.shape[2:])
        dn = lax.conv_dimension_numbers(a.shape, w.shape, dn_str)
        out = lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups)
        if maybe_b:
            shape = (1, -1, 1, 1, 1) if data_format == "NCDHW" else (1, 1, 1, 1, -1)
            out = out + maybe_b[0].reshape(shape)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(f, args, name="conv3d")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, output_size=None,
                     data_format="NCHW", name=None):
    """weight layout: [in_c, out_c/groups, kh, kw] (ref conv_transpose_op.cc)."""
    n = 2
    strides = _norm_tuple(stride, n)
    dilations = _norm_tuple(dilation, n)
    out_pad = _norm_tuple(output_padding, n)

    def f(a, w, *maybe_b):
        if data_format == "NHWC":
            a_nchw = jnp.transpose(a, (0, 3, 1, 2))
        else:
            a_nchw = a
        pad = _conv_padding(padding, n, strides, dilations, w.shape[2:])
        if isinstance(pad, str):
            pad_list = [(0, 0)] * n if pad == "VALID" else None
            if pad_list is None:
                raise ValueError("SAME padding unsupported for conv_transpose")
            pad = pad_list
        kh = [((w.shape[2 + i] - 1) * dilations[i] + 1) for i in range(n)]
        trans_pad = [
            (kh[i] - 1 - pad[i][0], kh[i] - 1 - pad[i][1] + out_pad[i])
            for i in range(n)]
        # grouped transpose conv: weight [in_c, out_c/g, kh, kw]
        w_flip = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups == 1:
            w_t = jnp.transpose(w_flip, (1, 0, 2, 3))  # -> [out_c, in_c, kh, kw]
            dn = lax.conv_dimension_numbers(a_nchw.shape, w_t.shape,
                                            ("NCHW", "OIHW", "NCHW"))
            out = lax.conv_general_dilated(
                a_nchw, w_t, window_strides=(1, 1), padding=trans_pad,
                lhs_dilation=strides, rhs_dilation=dilations,
                dimension_numbers=dn)
        else:
            ic = a_nchw.shape[1]
            icg = ic // groups
            outs = []
            for g in range(groups):
                wg = w_flip[g * icg:(g + 1) * icg]
                wg_t = jnp.transpose(wg, (1, 0, 2, 3))
                dn = lax.conv_dimension_numbers(
                    (a_nchw.shape[0], icg) + a_nchw.shape[2:], wg_t.shape,
                    ("NCHW", "OIHW", "NCHW"))
                outs.append(lax.conv_general_dilated(
                    a_nchw[:, g * icg:(g + 1) * icg], wg_t, window_strides=(1, 1),
                    padding=trans_pad, lhs_dilation=strides,
                    rhs_dilation=dilations, dimension_numbers=dn))
            out = jnp.concatenate(outs, axis=1)
        if maybe_b:
            out = out + maybe_b[0].reshape(1, -1, 1, 1)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(f, args, name="conv2d_transpose")


def _pool(x, ksize, strides, padding, data_format, reducer, init, name,
          ceil_mode=False, count_include_pad=True, average=False):
    n = 2
    ksize = _norm_tuple(ksize, n)
    strides = _norm_tuple(strides or ksize, n)

    def f(a):
        if data_format == "NCHW":
            dims = (1, 1) + ksize
            strd = (1, 1) + strides
        else:
            dims = (1,) + ksize + (1,)
            strd = (1,) + strides + (1,)
        pad = _conv_padding(padding, n, strides, (1, 1), ksize)
        if isinstance(pad, str):
            pad_cfg = pad
        else:
            if data_format == "NCHW":
                pad_cfg = [(0, 0), (0, 0)] + list(pad)
            else:
                pad_cfg = [(0, 0)] + list(pad) + [(0, 0)]
        out = lax.reduce_window(a, init(a.dtype), reducer, dims, strd, pad_cfg)
        if average:
            if count_include_pad or (isinstance(pad, str) and pad == "VALID"):
                denom = np.prod(ksize)
                out = out / denom
            else:
                onesw = lax.reduce_window(jnp.ones_like(a), 0.0, lax.add, dims,
                                          strd, pad_cfg)
                out = out / onesw
        return out

    return apply(f, (x,), name=name)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, data_format, lax.max,
                 lambda dt: -jnp.inf if jnp.issubdtype(dt, jnp.floating)
                 else jnp.iinfo(dt).min,
                 "max_pool2d", ceil_mode=ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               count_include_pad=True, divisor_override=None,
               data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, data_format, lax.add,
                 lambda dt: jnp.zeros([], dt).item() if False else 0.0,
                 "avg_pool2d", ceil_mode=ceil_mode,
                 count_include_pad=count_include_pad, average=True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out_hw = _norm_tuple(output_size, 2)

    def f(a):
        if data_format == "NCHW":
            h_axis, w_axis = 2, 3
        else:
            h_axis, w_axis = 1, 2
        ih, iw = a.shape[h_axis], a.shape[w_axis]
        oh, ow = out_hw
        if ih % oh == 0 and iw % ow == 0:
            # reshape-mean fast path
            if data_format == "NCHW":
                r = a.reshape(a.shape[0], a.shape[1], oh, ih // oh, ow, iw // ow)
                return r.mean(axis=(3, 5))
            r = a.reshape(a.shape[0], oh, ih // oh, ow, iw // ow, a.shape[-1])
            return r.mean(axis=(2, 4))
        # general: per-output-bin mean via cumsum trick is overkill; use resize
        raise NotImplementedError(
            "adaptive pooling with non-divisible sizes not supported")

    return apply(f, (x,), name="adaptive_avg_pool2d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out_hw = _norm_tuple(output_size, 2)

    def f(a):
        ih, iw = a.shape[2], a.shape[3]
        oh, ow = out_hw
        if ih % oh == 0 and iw % ow == 0:
            r = a.reshape(a.shape[0], a.shape[1], oh, ih // oh, ow, iw // ow)
            return r.max(axis=(3, 5))
        raise NotImplementedError
    return apply(f, (x,), name="adaptive_max_pool2d")


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, name=None):
    t = x.unsqueeze(-1) if isinstance(x, Tensor) else Tensor(x)
    out = max_pool2d(t, (int(kernel_size) if isinstance(kernel_size, int)
                         else kernel_size[0], 1),
                     (int(stride) if isinstance(stride, (int, type(None)))
                      and stride else (stride[0] if stride else None), 1)
                     if stride else None,
                     padding=(padding if isinstance(padding, int) else padding[0],
                              0))
    return out.squeeze(-1)


def avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               count_include_pad=True, name=None):
    t = x.unsqueeze(-1)
    out = avg_pool2d(t, (kernel_size if isinstance(kernel_size, int)
                         else kernel_size[0], 1),
                     (stride if isinstance(stride, int) else None, 1)
                     if stride else None,
                     padding=(padding if isinstance(padding, int) else padding[0],
                              0), count_include_pad=count_include_pad)
    return out.squeeze(-1)


# ----------------------------------------------------------------- norm

def _batch_norm_raw(v, rm, rv, *wb, ch_axis=1, momentum=0.9, epsilon=1e-5,
                    training=False):
    """Single batch_norm op: y + updated running stats as explicit outputs
    (ref operators/batch_norm_op.cc MeanOut/VarianceOut in-place outputs).
    Eval mode passes the stats through unchanged."""
    ch = ch_axis % v.ndim
    shape = [1] * v.ndim
    shape[ch] = v.shape[ch]
    if training:
        reduce_axes = tuple(i for i in range(v.ndim) if i != ch)
        m = jnp.mean(v, axis=reduce_axes)
        var = jnp.var(v, axis=reduce_axes)
        new_rm = momentum * rm + (1 - momentum) * m
        new_rv = momentum * rv + (1 - momentum) * var
        inv = lax.rsqrt(var.reshape(shape) + epsilon)
        out = (v - m.reshape(shape)) * inv
    else:
        new_rm, new_rv = rm, rv
        inv = lax.rsqrt(rv.reshape(shape) + epsilon)
        out = (v - rm.reshape(shape)) * inv
    if wb:
        out = out * wb[0].reshape(shape)
        if len(wb) > 1:
            out = out + wb[1].reshape(shape)
    return out, lax.stop_gradient(new_rm), lax.stop_gradient(new_rv)


register_op("batch_norm", _batch_norm_raw)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    """ref operators/batch_norm_op.cc. Running stats are explicit op outputs;
    the wrapper writes them back onto the buffer Tensors (captured by
    functional_call and by the static recorder via alias_output)."""
    ch_axis = 1 if data_format in ("NCHW", "NCL", "NCDHW") else -1
    use_batch_stats = training and not use_global_stats
    args = [x, running_mean, running_var]
    if weight is None and bias is not None:
        weight = Tensor(jnp.ones_like(as_array(bias)))   # shift-only affine
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    outs = apply(_batch_norm_raw, tuple(args),
                 {"ch_axis": int(ch_axis), "momentum": float(momentum),
                  "epsilon": float(epsilon),
                  "training": bool(use_batch_stats)}, name="batch_norm")
    y, new_rm, new_rv = outs
    if use_batch_stats:
        rec = state.get_static_recorder()
        if rec is not None:
            rec.alias_output(new_rm, running_mean)
            rec.alias_output(new_rv, running_var)
        running_mean._data = new_rm._data
        running_var._data = new_rv._data
    return y


def _layer_norm_raw(a, *wb, nd=1, epsilon=1e-5):
    axes = tuple(range(a.ndim - nd, a.ndim))
    m = jnp.mean(a, axis=axes, keepdims=True)
    v = jnp.var(a, axis=axes, keepdims=True)
    out = (a - m) * lax.rsqrt(v + epsilon)
    if wb:
        out = out * wb[0]
        if len(wb) > 1:
            out = out + wb[1]
    return out


register_op("layer_norm", _layer_norm_raw)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, numbers.Number):
        normalized_shape = (normalized_shape,)
    nd = len(tuple(normalized_shape))
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply(_layer_norm_raw, tuple(args),
                 {"nd": nd, "epsilon": float(epsilon)}, name="layer_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    def f(a, *wb):
        axes = tuple(range(2, a.ndim))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * lax.rsqrt(v + eps)
        if wb:
            shape = (1, -1) + (1,) * (a.ndim - 2)
            out = out * wb[0].reshape(shape)
            if len(wb) > 1:
                out = out + wb[1].reshape(shape)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply(f, tuple(args), name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    def f(a, *wb):
        n, c = a.shape[0], a.shape[1]
        g = num_groups
        r = a.reshape((n, g, c // g) + a.shape[2:])
        axes = tuple(range(2, r.ndim))
        m = jnp.mean(r, axis=axes, keepdims=True)
        v = jnp.var(r, axis=axes, keepdims=True)
        out = ((r - m) * lax.rsqrt(v + epsilon)).reshape(a.shape)
        if wb:
            shape = (1, c) + (1,) * (a.ndim - 2)
            out = out * wb[0].reshape(shape)
            if len(wb) > 1:
                out = out + wb[1].reshape(shape)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply(f, tuple(args), name="group_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        nrm = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis,
                                keepdims=True), 1.0 / p)
        return a / jnp.maximum(nrm, epsilon)
    return apply(f, (x,), name="normalize")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(a):
        sq = jnp.square(a)
        half = size // 2
        pad_cfg = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2)
        padded = jnp.pad(sq, pad_cfg)
        window = sum(padded[:, i:i + a.shape[1]] for i in range(size))
        return a / jnp.power(k + alpha * window, beta)
    return apply(f, (x,), name="local_response_norm")


# ----------------------------------------------------------------- losses

def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    """ref operators/softmax_with_cross_entropy_op.cc — fused log_softmax + NLL."""
    args = (input, label) if weight is None else (input, label, weight)
    return apply(_cross_entropy_raw, args,
                 {"ignore_index": int(ignore_index), "reduction": reduction,
                  "soft_label": bool(soft_label), "axis": int(axis),
                  "use_softmax": bool(use_softmax)}, name="cross_entropy")


def _cross_entropy_raw(logits, lab, *maybe_w, ignore_index=-100,
                       reduction="mean", soft_label=False, axis=-1,
                       use_softmax=True):
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(logits, 1e-30))
    if soft_label:
        per = -jnp.sum(lab * logp, axis=axis)
    else:
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == logp.ndim:  # [N,1] style labels
            lab_i = jnp.squeeze(lab_i, axis=axis)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        per = -jnp.take_along_axis(logp, jnp.expand_dims(safe, axis),
                                   axis=axis)
        per = jnp.squeeze(per, axis=axis)
        if maybe_w:
            w = jnp.take(maybe_w[0], safe)
            per = per * w
        per = jnp.where(valid, per, 0.0)
        if reduction == "mean":
            if maybe_w:
                w = jnp.take(maybe_w[0], safe)
                denom = jnp.sum(jnp.where(valid, w, 0.0))
            else:
                denom = jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
            return jnp.sum(per) / denom
    if reduction == "mean":
        return jnp.mean(per)
    if reduction == "sum":
        return jnp.sum(per)
    return per


register_op("cross_entropy", _cross_entropy_raw)


softmax_with_cross_entropy = cross_entropy


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def f(logp, lab, *maybe_w):
        lab_i = lab.astype(jnp.int32)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        per = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        if maybe_w:
            per = per * jnp.take(maybe_w[0], safe)
        per = jnp.where(valid, per, 0.0)
        if reduction == "mean":
            denom = (jnp.sum(jnp.take(maybe_w[0], safe) * valid) if maybe_w
                     else jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0))
            return jnp.sum(per) / denom
        if reduction == "sum":
            return jnp.sum(per)
        return per
    args = (input, label) if weight is None else (input, label, weight)
    return apply(f, args, name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    def f(a, b):
        d = jnp.square(a - b)
        if reduction == "mean":
            return jnp.mean(d)
        if reduction == "sum":
            return jnp.sum(d)
        return d
    return apply(f, (input, label), name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        if reduction == "mean":
            return jnp.mean(d)
        if reduction == "sum":
            return jnp.sum(d)
        return d
    return apply(f, (input, label), name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        l = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        if reduction == "mean":
            return jnp.mean(l)
        if reduction == "sum":
            return jnp.sum(l)
        return l
    return apply(f, (input, label), name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *maybe_w):
        per = -(y * jnp.log(jnp.maximum(p, 1e-12))
                + (1 - y) * jnp.log(jnp.maximum(1 - p, 1e-12)))
        if maybe_w:
            per = per * maybe_w[0]
        if reduction == "mean":
            return jnp.mean(per)
        if reduction == "sum":
            return jnp.sum(per)
        return per
    args = (input, label) if weight is None else (input, label, weight)
    return apply(f, args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, y, *rest):
        i = 0
        w = rest[i] if weight is not None else None
        if weight is not None:
            i += 1
        pw = rest[i] if pos_weight is not None else None
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        per = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if pw is not None:
            log_w = (pw - 1) * y + 1
            per = per * log_w
        if w is not None:
            per = per * w
        if reduction == "mean":
            return jnp.mean(per)
        if reduction == "sum":
            return jnp.sum(per)
        return per
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply(f, tuple(args), name="bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):
    def f(logp, y):
        per = y * (jnp.log(jnp.maximum(y, 1e-12)) - logp)
        if reduction == "mean":
            return jnp.mean(per)
        if reduction == "batchmean":
            return jnp.sum(per) / logp.shape[0]
        if reduction == "sum":
            return jnp.sum(per)
        return per
    return apply(f, (input, label), name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def f(a, b, y):
        per = jnp.maximum(-y * (a - b) + margin, 0.0)
        if reduction == "mean":
            return jnp.mean(per)
        if reduction == "sum":
            return jnp.sum(per)
        return per
    return apply(f, (input, other, label), name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(a, y):
        per = jnp.where(y == 1, a, jnp.maximum(margin - a, 0.0))
        if reduction == "mean":
            return jnp.mean(per)
        if reduction == "sum":
            return jnp.sum(per)
        return per
    return apply(f, (input, label), name="hinge_embedding_loss")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.maximum(jnp.linalg.norm(a, axis=axis)
                          * jnp.linalg.norm(b, axis=axis), eps)
        return num / den
    return apply(f, (x1, x2), name="cosine_similarity")


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), (input, label),
                 name="square_error_cost")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, *maybe_n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        per = a_t * jnp.power(1 - p_t, gamma) * ce
        if maybe_n:
            per = per / maybe_n[0]
        if reduction == "mean":
            return jnp.mean(per)
        if reduction == "sum":
            return jnp.sum(per)
        return per
    args = (logit, label) if normalizer is None else (logit, label, normalizer)
    return apply(f, args, name="sigmoid_focal_loss")


# ----------------------------------------------------------------- padding etc.

def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    def f(a):
        p = [int(v) for v in pad]
        if len(p) == 2 * a.ndim:
            cfg = [(p[2 * i], p[2 * i + 1]) for i in range(a.ndim)]
        else:
            # paddle: pad applies to last len(p)//2 spatial dims
            # for NCHW 4-d input with 4 pads: [left,right,top,bottom] on W,H
            n_spatial = len(p) // 2
            cfg = [(0, 0)] * a.ndim
            if data_format.startswith("NC"):
                dims = list(range(a.ndim - n_spatial, a.ndim))
            else:
                dims = list(range(1, 1 + n_spatial))
            # paddle order: innermost (last) dim first
            for i, d in enumerate(reversed(dims)):
                cfg[d] = (p[2 * i], p[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, cfg, mode="constant", constant_values=value)
        return jnp.pad(a, cfg, mode=jmode)
    return apply(f, (x,), name="pad")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    p = _norm_tuple(paddings, 2)
    d = _norm_tuple(dilations, 2)

    def f(a):
        n, c, h, w = a.shape
        patches = lax.conv_general_dilated_patches(
            a, filter_shape=k, window_strides=s,
            padding=[(p[0], p[0]), (p[1], p[1])], rhs_dilation=d,
            dimension_numbers=lax.conv_dimension_numbers(
                a.shape, (c, c, k[0], k[1]), ("NCHW", "OIHW", "NCHW")))
        # -> [N, C*kh*kw, L]
        return patches.reshape(n, c * k[0] * k[1], -1)
    return apply(f, (x,), name="unfold")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            spatial = (h, w)
        else:
            n, h, w, c = a.shape
            spatial = (h, w)
        if size is not None:
            out_hw = tuple(int(v) for v in
                           (size.tolist() if isinstance(size, Tensor) else size))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else (scale_factor, scale_factor)
            out_hw = (int(spatial[0] * sf[0]), int(spatial[1] * sf[1]))
        method = {"nearest": "nearest", "bilinear": "linear",
                  "bicubic": "cubic", "area": "linear"}[mode]
        if data_format == "NCHW":
            shape = (n, c) + out_hw
        else:
            shape = (n,) + out_hw + (c,)
        return jax.image.resize(a, shape, method=method)
    return apply(f, (x,), name="interpolate")


upsample = interpolate


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        n, c, h, w = a.shape
        oc = c // (r * r)
        out = a.reshape(n, oc, r, r, h, w)
        out = out.transpose(0, 1, 4, 2, 5, 3)
        return out.reshape(n, oc, h * r, w * r)
    return apply(f, (x,), name="pixel_shuffle")


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    def f(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        r = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([r[:, 1:, :fold], jnp.zeros_like(r[:, -1:, :fold])],
                               axis=1)
        right = jnp.concatenate([jnp.zeros_like(r[:, :1, fold:2 * fold]),
                                 r[:, :-1, fold:2 * fold]], axis=1)
        rest = r[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
    return apply(f, (x,), name="temporal_shift")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    def f(a, g):
        n, c, h, w = a.shape
        gx = (g[..., 0] + 1) * (w - 1) / 2 if align_corners \
            else ((g[..., 0] + 1) * w - 1) / 2
        gy = (g[..., 1] + 1) * (h - 1) / 2 if align_corners \
            else ((g[..., 1] + 1) * h - 1) / 2
        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1

        def sample(yy, xx):
            yy_c = jnp.clip(yy, 0, h - 1)
            xx_c = jnp.clip(xx, 0, w - 1)
            v = a[jnp.arange(n)[:, None, None], :, yy_c, xx_c]  # [N,Hg,Wg,C]
            if padding_mode == "zeros":
                inb = ((yy >= 0) & (yy < h) & (xx >= 0) & (xx < w))[..., None]
                v = jnp.where(inb, v, 0.0)
            return v

        wa = ((x1 - gx) * (y1 - gy))[..., None]
        wb = ((x1 - gx) * (gy - y0))[..., None]
        wc = ((gx - x0) * (y1 - gy))[..., None]
        wd = ((gx - x0) * (gy - y0))[..., None]
        out = (sample(y0, x0) * wa + sample(y1, x0) * wb
               + sample(y0, x1) * wc + sample(y1, x1) * wd)
        return out.transpose(0, 3, 1, 2)
    return apply(f, (x, grid), name="grid_sample")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def f(th):
        n, _, h, w = [int(v) for v in (out_shape.tolist()
                                       if isinstance(out_shape, Tensor)
                                       else out_shape)]
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [H,W,3]
        return jnp.einsum("nij,hwj->nhwi", th, base)
    return apply(f, (theta,), name="affine_grid")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(y, *maybe_p):
        k = y.shape[-1]
        if maybe_p:
            return (1 - epsilon) * y + epsilon * maybe_p[0]
        return (1 - epsilon) * y + epsilon / k
    args = (label,) if prior_dist is None else (label, prior_dist)
    return apply(f, args, name="label_smooth")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def f(a, p, y):
        sim = jnp.matmul(a, p.T)
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        same = same / jnp.sum(same, axis=1, keepdims=True)
        ce = jnp.mean(-jnp.sum(same * jax.nn.log_softmax(sim, axis=1), axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(a), 1))
                        + jnp.mean(jnp.sum(jnp.square(p), 1))) * 0.25
        return ce + reg
    return apply(f, (anchor, positive, labels), name="npair_loss")


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def f(a):
        out = jnp.zeros(a.shape + (a.shape[-1],), a.dtype)
        idx = jnp.arange(a.shape[-1])
        return out.at[..., idx, idx].set(a)
    return apply(f, (x,), name="diag_embed")


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    ml = int(maxlen) if maxlen is not None else int(np.asarray(
        as_array(lengths)).max())

    def f(l):
        return (jnp.arange(ml)[None, :] < l[:, None]).astype(convert_dtype(dtype))
    return apply(f, (lengths,), differentiable=False, name="sequence_mask")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """ref nn/functional/distance.py: p-norm of (x - y) over the last
    axis. The reference's p_norm kernel uses `epsilon` only in the
    GRADIENT denominator (p_norm_op.h PnormGradKernel), never the
    forward — kept in the signature for API parity; autodiff handles the
    norm-at-zero subgradient here."""
    def f(x_, y_):
        return jnp.linalg.norm(x_ - y_, ord=p, axis=-1, keepdims=keepdim)

    return apply(f, (x, y), name="pairwise_distance")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (ref operators/warpctc_op.cc / paddle.nn.functional.ctc_loss).

    log_probs: [T, B, C] RAW logits (log_softmax applied internally, like
    the reference's warpctc). labels: [B, Lmax] padded int labels.
    input_lengths/label_lengths: [B] ints.

    TPU-native: the alpha recursion runs as one lax.scan over time in log
    space with static shapes ([B, 2*Lmax+1] state); per-sample lengths are
    handled by masking, so one compiled program serves the whole batch.
    Gradients come from autodiff through the scan (the reference ships a
    hand-written backward; XLA differentiates the recursion directly).
    """
    def f(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        Lmax = lab.shape[1]
        S = 2 * Lmax + 1
        logp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        neg_inf = jnp.float32(-1e30)

        # extended label sequence l' = [blank, l1, blank, l2, ..., blank]
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        # transition-2 allowed where l'_s != blank and l'_s != l'_{s-2}
        ext_m2 = jnp.concatenate(
            [jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
        can_skip = (ext != blank) & (ext != ext_m2)

        def emit(t_logp):
            # t_logp: [B, C] -> per-extended-position emission [B, S]
            return jnp.take_along_axis(t_logp, ext, axis=1)

        alpha0 = jnp.full((B, S), neg_inf)
        e0 = emit(logp[0])
        alpha0 = alpha0.at[:, 0].set(e0[:, 0])
        if S > 1:      # Lmax=0 (all-blank targets) has only position 0
            alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, e0[:, 1],
                                                   neg_inf))

        def step(alpha, t_logp_t):
            t_logp, t = t_logp_t
            if S > 1:
                prev1 = jnp.concatenate(
                    [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
                prev2 = jnp.concatenate(
                    [jnp.full((B, 2), neg_inf),
                     alpha[:, :max(S - 2, 0)]], axis=1)[:, :S]
                prev2 = jnp.where(can_skip, prev2, neg_inf)
                merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
            else:      # Lmax=0: only the all-blank path exists
                merged = alpha
            new = merged + emit(t_logp)
            # freeze finished samples (t >= input_length)
            active = (t < in_len)[:, None]
            return jnp.where(active, new, alpha), None

        ts = jnp.arange(1, T)
        alpha, _ = jax.lax.scan(step, alpha0, (logp[1:], ts))

        # final: logsumexp of positions S-1 (last blank) and S-2 (last label)
        s_last = 2 * lab_len.astype(jnp.int32)        # index of last blank
        a_last = jnp.take_along_axis(alpha, s_last[:, None], axis=1)[:, 0]
        s_lab = jnp.maximum(s_last - 1, 0)
        a_lab = jnp.where(
            lab_len > 0,
            jnp.take_along_axis(alpha, s_lab[:, None], axis=1)[:, 0],
            neg_inf)
        nll = -jnp.logaddexp(a_last, a_lab)
        if norm_by_times:
            nll = nll / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        if reduction == "mean":
            # paddle mean: divide per-sample loss by label_length first
            return jnp.mean(nll / jnp.maximum(
                lab_len.astype(jnp.float32), 1.0))
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return apply(f, (log_probs, labels, input_lengths, label_lengths),
                 name="ctc_loss")


def gather_tree(ids, parents):
    """Reconstruct full beam-search sequences from per-step token ids and
    parent beam indices (ref operators/gather_tree_op.cc; both [T, B, K]).
    TPU-native: one reverse lax.scan walking the parent chain — no
    per-(batch, beam) host loops."""
    def f(ids_, par_):
        T, B, K = ids_.shape
        par_ = par_.astype(jnp.int32)

        def step(beams, xs):
            ids_t, par_t = xs
            out_t = jnp.take_along_axis(ids_t, beams, axis=-1)
            prev = jnp.take_along_axis(par_t, beams, axis=-1)
            return prev, out_t

        init = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32), (B, K))
        _, outs = jax.lax.scan(step, init, (ids_, par_), reverse=True)
        return outs

    return apply(f, (ids, parents), differentiable=False,
                 name="gather_tree")
