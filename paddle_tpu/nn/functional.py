"""nn.functional — activations, conv/pool, norm, losses, embedding, dropout
(ref python/paddle/nn/functional/* and the kernels in paddle/fluid/operators/:
activation_op.cc, conv_cudnn_op.cu, pool_op, batch_norm_op, layer_norm_op,
softmax_with_cross_entropy_op, dropout_op, lookup_table_v2_op).

Convs ride lax.conv_general_dilated (MXU path); XLA picks TPU-optimal layouts so
both NCHW (paddle default) and NHWC are accepted.
"""
import functools
import math
import numbers
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..framework import state
from ..framework.dtype import convert_dtype
from ..framework.tensor import Tensor
from ..ops.dispatch import apply, as_array, register_op

# ----------------------------------------------------------------- activations


def _unary(fn, name):
    register_op(name, fn)

    def op(x, name=None, _opname=name):
        return apply(fn, (x,), name=_opname)
    op.__name__ = name
    return op


relu = _unary(jax.nn.relu, "relu")
relu6 = _unary(jax.nn.relu6, "relu6")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
tanh = _unary(jnp.tanh, "tanh")
silu = _unary(jax.nn.silu, "silu")
swish = silu
mish = _unary(lambda a: a * jnp.tanh(jax.nn.softplus(a)), "mish")
hardswish = _unary(jax.nn.hard_swish, "hardswish")
hardsigmoid = _unary(lambda a: jnp.clip(a / 6.0 + 0.5, 0.0, 1.0), "hardsigmoid")
tanhshrink = _unary(lambda a: a - jnp.tanh(a), "tanhshrink")


def _gelu_raw(a, approximate=False):
    return jax.nn.gelu(a, approximate=approximate)


register_op("gelu", _gelu_raw)


def gelu(x, approximate=False, name=None):
    return apply(_gelu_raw, (x,), {"approximate": bool(approximate)},
                 name="gelu")


def _leaky_relu_raw(a, negative_slope=0.01):
    return jax.nn.leaky_relu(a, negative_slope)


register_op("leaky_relu", _leaky_relu_raw)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(_leaky_relu_raw, (x,),
                 {"negative_slope": float(negative_slope)}, name="leaky_relu")


def _elu_raw(a, alpha=1.0):
    return jax.nn.elu(a, alpha)


def _celu_raw(a, alpha=1.0):
    return jax.nn.celu(a, alpha)


def _selu_raw(a, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(a > 0, a, alpha * jnp.expm1(a))


def _prelu_raw(a, w, data_format="NCHW"):
    if w.size == 1:
        return jnp.where(a > 0, a, w.reshape(()) * a)
    ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
    shape = [1] * a.ndim
    shape[ch_axis] = w.size
    return jnp.where(a > 0, a, w.reshape(shape) * a)


def _hardtanh_raw(a, lo=-1.0, hi=1.0):
    return jnp.clip(a, lo, hi)


def _hardshrink_raw(a, threshold=0.5):
    return jnp.where(jnp.abs(a) > threshold, a, 0.0)


def _softshrink_raw(a, threshold=0.5):
    return jnp.where(a > threshold, a - threshold,
                     jnp.where(a < -threshold, a + threshold, 0.0))


def _softplus_raw(a, beta=1.0, threshold=20.0):
    return jnp.where(a * beta > threshold, a,
                     jax.nn.softplus(a * beta) / beta)


def _softsign_raw(a):
    return a / (1 + jnp.abs(a))


def _maxout_raw(a, groups=1, axis=1):
    c = a.shape[axis]
    new_shape = list(a.shape)
    new_shape[axis] = c // groups
    new_shape.insert(axis + 1, groups)
    return jnp.max(a.reshape(new_shape), axis=axis + 1)


register_op("elu", _elu_raw)
register_op("celu", _celu_raw)
register_op("selu", _selu_raw)
register_op("prelu", _prelu_raw)
register_op("hardtanh", _hardtanh_raw)
register_op("hardshrink", _hardshrink_raw)
register_op("softshrink", _softshrink_raw)
register_op("softplus", _softplus_raw)
register_op("softsign", _softsign_raw)
register_op("maxout", _maxout_raw)


def elu(x, alpha=1.0, name=None):
    return apply(_elu_raw, (x,), {"alpha": float(alpha)}, name="elu")


def celu(x, alpha=1.0, name=None):
    return apply(_celu_raw, (x,), {"alpha": float(alpha)}, name="celu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(_selu_raw, (x,),
                 {"scale": float(scale), "alpha": float(alpha)}, name="selu")


def prelu(x, weight, data_format="NCHW", name=None):
    return apply(_prelu_raw, (x, weight), {"data_format": str(data_format)},
                 name="prelu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(_hardtanh_raw, (x,), {"lo": float(min), "hi": float(max)},
                 name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply(_hardshrink_raw, (x,), {"threshold": float(threshold)},
                 name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply(_softshrink_raw, (x,), {"threshold": float(threshold)},
                 name="softshrink")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(_softplus_raw, (x,),
                 {"beta": float(beta), "threshold": float(threshold)},
                 name="softplus")


def softsign(x, name=None):
    return apply(_softsign_raw, (x,), name="softsign")


def maxout(x, groups, axis=1, name=None):
    return apply(_maxout_raw, (x,),
                 {"groups": int(groups), "axis": int(axis)}, name="maxout")


def _softmax_raw(a, axis=-1, to_dtype=None):
    if to_dtype is not None:
        a = a.astype(convert_dtype(to_dtype))
    return jax.nn.softmax(a, axis=axis)


register_op("softmax", _softmax_raw)


def softmax(x, axis=-1, dtype=None, name=None):
    return apply(_softmax_raw, (x,),
                 {"axis": int(axis), "to_dtype": None if dtype is None else
                  str(np.dtype(convert_dtype(dtype)))}, name="softmax")


def _log_softmax_raw(a, axis=-1, to_dtype=None):
    if to_dtype is not None:
        a = a.astype(convert_dtype(to_dtype))
    return jax.nn.log_softmax(a, axis=axis)


register_op("log_softmax", _log_softmax_raw)


def log_softmax(x, axis=-1, dtype=None, name=None):
    return apply(_log_softmax_raw, (x,),
                 {"axis": int(axis), "to_dtype": None if dtype is None else
                  str(np.dtype(convert_dtype(dtype)))}, name="log_softmax")


def _gumbel_softmax_raw(a, key, temperature=1.0, hard=False, axis=-1):
    g = -jnp.log(-jnp.log(
        jax.random.uniform(key, tuple(a.shape)) + 1e-20))
    y = jax.nn.softmax((a + g) / temperature, axis=axis)
    if hard:
        # straight-through: one-hot forward, soft gradient
        idx = jnp.argmax(y, axis=axis)
        onehot = jax.nn.one_hot(idx, y.shape[axis], axis=axis, dtype=y.dtype)
        y = onehot + y - lax.stop_gradient(y)
    return y


register_op("gumbel_softmax", _gumbel_softmax_raw)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    # rng op: key is input #1 + "__rng__" salt, same replay contract as dropout
    return apply(_gumbel_softmax_raw, (x, Tensor(state.next_rng_key())),
                 {"temperature": float(temperature), "hard": bool(hard),
                  "axis": int(axis), "__rng__": True}, name="gumbel_softmax")


# ----------------------------------------------------------------- linear / emb

def _linear_raw(a, w, b=None):
    out = jnp.matmul(a, w)
    return out if b is None else out + b


register_op("linear", _linear_raw)


def linear(x, weight, bias=None, name=None):
    """paddle weight layout: [in_features, out_features] (ref nn/functional/common.py:1419)."""
    if bias is None:
        return apply(_linear_raw, (x, weight), name="linear")
    return apply(_linear_raw, (x, weight, bias), name="linear")


def _embedding_raw(idx, w, padding_idx=None):
    out = jnp.take(w, idx, axis=0)
    if padding_idx is not None:
        mask = (idx == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


register_op("embedding", _embedding_raw)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Device-side gather (TPU: embedding lookups stay on-chip; host-resident
    sparse tables are the PS path, see distributed/ps). sparse=True makes the
    EAGER backward produce a SelectedRows gradient on `weight` — O(batch*dim)
    instead of O(vocab*dim) (ref lookup_table_v2_op is_sparse grad; under
    jit, XLA's fused scatter-add already gives this, so the flag only
    changes the eager tape)."""
    if padding_idx is not None and padding_idx < 0:
        # paddle semantics: negative pad indexes from the end of the table
        padding_idx = int(as_array(weight).shape[0]) + int(padding_idx)
    if sparse and not state.is_functional_mode() and state.is_grad_enabled() \
            and isinstance(weight, Tensor) and not weight.stop_gradient \
            and weight._node is None:
        # leaf tables only: a non-leaf weight's producer holds a jax vjp
        # that cannot consume a SelectedRows cotangent
        return _sparse_embedding_eager(x, weight, padding_idx)
    return apply(_embedding_raw, (x, weight),
                 {"padding_idx": None if padding_idx is None
                  else int(padding_idx)}, name="embedding")


def _sparse_embedding_eager(x, weight, padding_idx):
    """Eager gather whose GradNode emits SelectedRows for the table."""
    from ..framework.tape import GradNode
    from ..framework.selected_rows import SelectedRows
    ids = as_array(x)
    w = as_array(weight)
    out = _embedding_raw(ids, w, padding_idx=padding_idx)
    height = int(w.shape[0])      # don't capture w: it pins a stale table

    def vjp(cot):
        flat_ids = ids.ravel()
        vals = cot.reshape((-1,) + cot.shape[ids.ndim:])
        if padding_idx is not None:
            vals = jnp.where((flat_ids == padding_idx)[..., None], 0.0, vals)
        return (jnp.zeros_like(ids),          # ids: int input, skipped
                SelectedRows(flat_ids, vals, height))

    res = Tensor(out, stop_gradient=False)
    node = GradNode(vjp=vjp,
                    inputs=[x if isinstance(x, Tensor) else None, weight],
                    n_outputs=1, out_shapes=(out.shape,),
                    out_dtypes=(out.dtype,), name="sparse_embedding")
    res._node = node
    res._slot = 0
    return res


def one_hot(x, num_classes, name=None):
    from ..ops.manipulation import _one_hot_raw
    return apply(_one_hot_raw, (x,), {"num_classes": int(num_classes)},
                 differentiable=False, name="one_hot")


# ----------------------------------------------------------------- dropout

def _dropout_raw(v, key, p=0.5, axis=None, mode="upscale_in_train",
                 training=True):
    """rng-explicit dropout (ref operators/dropout_op.cc: seed attr + mask
    output; here the mask is derived from a key input so the static desc
    replays with fresh randomness per run)."""
    if not training or p == 0.0:
        return v
    shape = tuple(v.shape)
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        shape = tuple(s if i in axes else 1 for i, s in enumerate(v.shape))
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, v / (1.0 - p), 0.0)
    return jnp.where(keep, v, 0.0)


register_op("dropout", _dropout_raw)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    # same gating as apply(): in functional (jit-trace) mode the recorder is
    # inert and the eager fast path below is safe
    rec = None if state.is_functional_mode() else state.get_static_recorder()
    if (not training or p == 0.0) and rec is None:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = state.next_rng_key()
    if isinstance(axis, (list, tuple)):
        axis = [int(a) for a in axis]
    elif axis is not None:
        axis = int(axis)
    # "__rng__": True asks the recorder to salt this op so the Executor
    # re-derives the key input per run (dispatch strips dunder attrs before
    # calling the impl)
    return apply(_dropout_raw, (x, Tensor(key)),
                 {"p": float(p), "axis": axis, "mode": mode,
                  "training": bool(training), "__rng__": True},
                 name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def _alpha_dropout_raw(v, key, p=0.5):
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(v.shape))
    q = 1.0 - p
    coef_a = (q + alpha_p ** 2 * q * p) ** -0.5
    coef_b = -coef_a * alpha_p * p
    return coef_a * jnp.where(keep, v, alpha_p) + coef_b


register_op("alpha_dropout", _alpha_dropout_raw)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    return apply(_alpha_dropout_raw, (x, Tensor(state.next_rng_key())),
                 {"p": float(p), "__rng__": True}, name="alpha_dropout")


# ----------------------------------------------------------------- conv / pool

def _norm_tuple(v, n):
    if isinstance(v, numbers.Number):
        return (int(v),) * n
    v = tuple(int(i) for i in v)
    if len(v) == 1:
        return v * n
    return v


def _conv_padding(padding, n, strides, dilations, ksize):
    """paddle padding spec -> lax padding list. Supports int, list, 'SAME','VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, numbers.Number):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n:
        if isinstance(padding[0], (list, tuple)):
            return [tuple(p) for p in padding]
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:  # [before0, after0, before1, after1...]
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _convnd_raw(a, w, *maybe_b, n=2, stride=1, padding=0, dilation=1,
                groups=1, channels_last=False):
    """Shared N-d conv impl (ref conv_op.cc): weight [out_c, in_c/g, *k]."""
    strides = _norm_tuple(stride, n)
    dilations = _norm_tuple(dilation, n)
    spatial = "DHW"[3 - n:]
    if channels_last:
        dn_str = ("N" + spatial + "C", "OI" + spatial, "N" + spatial + "C")
    else:
        dn_str = ("NC" + spatial, "OI" + spatial, "NC" + spatial)
    pad = _conv_padding(padding, n, strides, dilations, w.shape[2:])
    dn = lax.conv_dimension_numbers(a.shape, w.shape, dn_str)
    out = lax.conv_general_dilated(
        a, w, window_strides=strides, padding=pad,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups)
    if maybe_b:
        shape = ((1,) + (1,) * n + (-1,) if channels_last
                 else (1, -1) + (1,) * n)
        out = out + maybe_b[0].reshape(shape)
    return out


def _conv1d_raw(a, w, *maybe_b, stride=1, padding=0, dilation=1, groups=1,
                channels_last=False):
    return _convnd_raw(a, w, *maybe_b, n=1, stride=stride, padding=padding,
                       dilation=dilation, groups=groups,
                       channels_last=channels_last)


def _conv2d_raw(a, w, *maybe_b, stride=1, padding=0, dilation=1, groups=1,
                channels_last=False):
    return _convnd_raw(a, w, *maybe_b, n=2, stride=stride, padding=padding,
                       dilation=dilation, groups=groups,
                       channels_last=channels_last)


def _conv3d_raw(a, w, *maybe_b, stride=1, padding=0, dilation=1, groups=1,
                channels_last=False):
    return _convnd_raw(a, w, *maybe_b, n=3, stride=stride, padding=padding,
                       dilation=dilation, groups=groups,
                       channels_last=channels_last)


register_op("conv1d", _conv1d_raw)
register_op("conv2d", _conv2d_raw)
register_op("conv3d", _conv3d_raw)


def _pad_attr(padding):
    if isinstance(padding, str):
        return padding
    if isinstance(padding, numbers.Number):
        return int(padding)
    return [list(int(i) for i in p) if isinstance(p, (list, tuple))
            else int(p) for p in padding]


def _stride_attr(v):
    if isinstance(v, numbers.Number):
        return int(v)
    return [int(i) for i in v]


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """weight layout: [out_c, in_c/groups, kh, kw] (paddle/ref conv_op.cc)."""
    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(_conv2d_raw, args,
                 {"stride": _stride_attr(stride), "padding": _pad_attr(padding),
                  "dilation": _stride_attr(dilation), "groups": int(groups),
                  "channels_last": data_format != "NCHW"}, name="conv2d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(_conv1d_raw, args,
                 {"stride": _stride_attr(stride), "padding": _pad_attr(padding),
                  "dilation": _stride_attr(dilation), "groups": int(groups),
                  "channels_last": data_format != "NCL"}, name="conv1d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(_conv3d_raw, args,
                 {"stride": _stride_attr(stride), "padding": _pad_attr(padding),
                  "dilation": _stride_attr(dilation), "groups": int(groups),
                  "channels_last": data_format != "NCDHW"}, name="conv3d")


def _conv2d_transpose_raw(a, w, *maybe_b, stride=1, padding=0,
                          output_padding=0, dilation=1, groups=1,
                          channels_last=False):
    """weight layout: [in_c, out_c/groups, kh, kw] (ref conv_transpose_op.cc).
    Thin layout shim over the shared N-d impl (_convnd_transpose_raw)."""
    if channels_last:
        a = jnp.transpose(a, (0, 3, 1, 2))
    out = _convnd_transpose_raw(a, w, *maybe_b, n=2, stride=stride,
                                padding=padding,
                                output_padding=output_padding,
                                dilation=dilation, groups=groups)
    if channels_last:
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


register_op("conv2d_transpose", _conv2d_transpose_raw)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, output_size=None,
                     data_format="NCHW", name=None):
    """weight layout: [in_c, out_c/groups, kh, kw] (ref conv_transpose_op.cc)."""
    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(_conv2d_transpose_raw, args,
                 {"stride": _stride_attr(stride), "padding": _pad_attr(padding),
                  "output_padding": _stride_attr(output_padding),
                  "dilation": _stride_attr(dilation), "groups": int(groups),
                  "channels_last": data_format != "NCHW"},
                 name="conv2d_transpose")


def _poolnd_raw(a, n=2, ksize=1, strides=None, padding=0,
                channels_last=False, average=False, count_include_pad=True,
                ceil_mode=False):
    """Shared 1/2/3-d pooling over lax.reduce_window (NCX or NXC).
    ceil_mode=True rounds the output size UP (ref pooling.cc
    AdaptEndIndex/ceil branch) by extending the high-edge padding;
    the extra cells never count toward an exclusive average (the ones
    window sees them as padding)."""
    ksize = _norm_tuple(ksize, n)
    strides = _norm_tuple(strides or ksize, n)
    if not channels_last:
        dims = (1, 1) + ksize
        strd = (1, 1) + strides
    else:
        dims = (1,) + ksize + (1,)
        strd = (1,) + strides + (1,)
    pad = _conv_padding(padding, n, strides, (1,) * n, ksize)
    if ceil_mode and not isinstance(pad, str):
        spatial = a.shape[1:1 + n] if channels_last else a.shape[2:2 + n]
        pad = [list(p) for p in pad]
        for i in range(n):
            H, (pl, ph) = spatial[i], pad[i]
            total = H + pl + ph
            out = -(-(total - ksize[i]) // strides[i]) + 1   # ceil count
            # a window starting entirely in the high pad is not a window
            # (torch/caffe clamp rule); without it stride > kernel emits
            # all-padding cells (-inf / 0-count NaN)
            if (out - 1) * strides[i] >= H + pl:
                out -= 1
            needed = (out - 1) * strides[i] + ksize[i]
            if needed > total:
                pad[i][1] += needed - total
        pad = [tuple(p) for p in pad]
    if isinstance(pad, str):
        pad_cfg = pad
    else:
        if not channels_last:
            pad_cfg = [(0, 0), (0, 0)] + list(pad)
        else:
            pad_cfg = [(0, 0)] + list(pad) + [(0, 0)]
    if average:
        reducer, init = lax.add, 0.0
    else:
        reducer = lax.max
        init = (-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                else jnp.iinfo(a.dtype).min)
    out = lax.reduce_window(a, init, reducer, dims, strd, pad_cfg)
    if average:
        if count_include_pad or (isinstance(pad, str) and pad == "VALID"):
            out = out / np.prod(ksize)
        else:
            onesw = lax.reduce_window(jnp.ones_like(a), 0.0, lax.add, dims,
                                      strd, pad_cfg)
            out = out / onesw
    return out


register_op("max_pool2d", functools.partial(_poolnd_raw, n=2, average=False))
register_op("avg_pool2d", functools.partial(_poolnd_raw, n=2, average=True))


def _pool(x, ksize, strides, padding, data_format, name,
          ceil_mode=False, count_include_pad=True, average=False):
    from ..ops.dispatch import OP_REGISTRY
    attrs = {"ksize": _stride_attr(ksize),
             "strides": None if strides is None else _stride_attr(strides),
             "padding": _pad_attr(padding),
             "channels_last": data_format != "NCHW"}
    if ceil_mode:
        attrs["ceil_mode"] = True
    if average:
        attrs["count_include_pad"] = bool(count_include_pad)
    return apply(OP_REGISTRY[name], (x,), attrs, name=name)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, data_format,
                 "max_pool2d", ceil_mode=ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               count_include_pad=True, divisor_override=None,
               data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, data_format,
                 "avg_pool2d", ceil_mode=ceil_mode,
                 count_include_pad=count_include_pad, average=True)


def _adaptive_bins(in_n, out_n):
    """Reference adaptive bins (ref pooling.cc AdaptStartIndex/EndIndex):
    bin i covers [floor(i*I/O), ceil((i+1)*I/O))."""
    i = np.arange(out_n)
    start = (i * in_n) // out_n
    end = -((-(i + 1) * in_n) // out_n)      # ceil div
    return start, end


def _adaptive_avg_mat(in_n, out_n, dtype):
    """[out_n, in_n] averaging matrix: the general adaptive mean becomes
    a matmul over each spatial axis — static (trace-time) bin layout,
    MXU-friendly, no data-dependent shapes."""
    start, end = _adaptive_bins(in_n, out_n)
    j = np.arange(in_n)
    m = ((j[None, :] >= start[:, None])
         & (j[None, :] < end[:, None])).astype(np.float32)
    m /= m.sum(1, keepdims=True)
    return jnp.asarray(m, dtype)


def _adaptive_avg_pool2d_raw(a, output_size=1, channels_last=False):
    out_hw = _norm_tuple(output_size, 2)
    if not channels_last:
        h_axis, w_axis = 2, 3
    else:
        h_axis, w_axis = 1, 2
    ih, iw = a.shape[h_axis], a.shape[w_axis]
    oh, ow = out_hw
    if ih % oh == 0 and iw % ow == 0:
        # reshape-mean fast path
        if not channels_last:
            r = a.reshape(a.shape[0], a.shape[1], oh, ih // oh, ow, iw // ow)
            return r.mean(axis=(3, 5))
        r = a.reshape(a.shape[0], oh, ih // oh, ow, iw // ow, a.shape[-1])
        return r.mean(axis=(2, 4))
    # general (non-divisible) sizes: contract each spatial axis with its
    # averaging matrix — two matmuls instead of gathers
    acc = jnp.float32 if a.dtype != jnp.float64 else jnp.float64
    wh = _adaptive_avg_mat(ih, oh, acc)
    ww = _adaptive_avg_mat(iw, ow, acc)
    af = a.astype(acc)
    if not channels_last:
        out = jnp.einsum("nchw,oh,pw->ncop", af, wh, ww)
    else:
        out = jnp.einsum("nhwc,oh,pw->nopc", af, wh, ww)
    return out.astype(a.dtype)


def _adaptive_max_pool2d_raw(a, output_size=1):
    out_hw = _norm_tuple(output_size, 2)
    ih, iw = a.shape[2], a.shape[3]
    oh, ow = out_hw
    if ih % oh == 0 and iw % ow == 0:
        r = a.reshape(a.shape[0], a.shape[1], oh, ih // oh, ow, iw // ow)
        return r.max(axis=(3, 5))
    # general sizes: bins are static at trace time but ragged; reduce per
    # output row/col with dynamic slices (O static, so the loop unrolls)
    hs, he = _adaptive_bins(ih, oh)
    ws, we = _adaptive_bins(iw, ow)
    rows = jnp.stack([a[:, :, s:e, :].max(axis=2)
                      for s, e in zip(hs, he)], axis=2)      # [N,C,oh,iw]
    return jnp.stack([rows[:, :, :, s:e].max(axis=3)
                      for s, e in zip(ws, we)], axis=3)


register_op("adaptive_avg_pool2d", _adaptive_avg_pool2d_raw)
register_op("adaptive_max_pool2d", _adaptive_max_pool2d_raw)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return apply(_adaptive_avg_pool2d_raw, (x,),
                 {"output_size": _stride_attr(output_size),
                  "channels_last": data_format != "NCHW"},
                 name="adaptive_avg_pool2d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return apply(_adaptive_max_pool2d_raw, (x,),
                 {"output_size": _stride_attr(output_size)},
                 name="adaptive_max_pool2d")


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, name=None):
    t = x.unsqueeze(-1) if isinstance(x, Tensor) else Tensor(x)
    out = max_pool2d(t, (int(kernel_size) if isinstance(kernel_size, int)
                         else kernel_size[0], 1),
                     (int(stride) if isinstance(stride, (int, type(None)))
                      and stride else (stride[0] if stride else None), 1)
                     if stride else None,
                     padding=(padding if isinstance(padding, int) else padding[0],
                              0), ceil_mode=ceil_mode)
    return out.squeeze(-1)


def avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               count_include_pad=True, name=None):
    t = x.unsqueeze(-1)
    out = avg_pool2d(t, (kernel_size if isinstance(kernel_size, int)
                         else kernel_size[0], 1),
                     (stride if isinstance(stride, int) else None, 1)
                     if stride else None,
                     padding=(padding if isinstance(padding, int) else padding[0],
                              0), ceil_mode=ceil_mode,
                     count_include_pad=count_include_pad)
    return out.squeeze(-1)


# ----------------------------------------------------------------- norm

def _batch_norm_raw(v, rm, rv, *wb, ch_axis=1, momentum=0.9, epsilon=1e-5,
                    training=False):
    """Single batch_norm op: y + updated running stats as explicit outputs
    (ref operators/batch_norm_op.cc MeanOut/VarianceOut in-place outputs).
    Eval mode passes the stats through unchanged."""
    ch = ch_axis % v.ndim
    shape = [1] * v.ndim
    shape[ch] = v.shape[ch]
    # stats in f32 (bf16 inputs must not accumulate in bf16), or in f64
    # when the caller is already double-precision (x64 mode). The f32
    # chain feeds ONLY the stats reductions: giving the apply its own
    # input-dtype chain keeps the convert fused inside the one stats
    # sweep — a shared f32 activation gets materialized by XLA as an
    # extra f32[N,C,H,W] output on the producing conv fusion (observed
    # on-chip: +10 ms/step on resnet50 b=128, ~410 MB per layer)
    stat_dt = v.dtype if v.dtype == jnp.float64 else jnp.float32
    if training:
        # Single-pass stats: the centered sum and sum-of-squares are
        # INDEPENDENT reductions over the same input, so XLA
        # sibling-fuses them into one HBM sweep; the mean-then-var form
        # chains two sweeps (var needs the mean first) and dominated the
        # resnet50 step on-chip (53 BN layers — see
        # docs/perf/traces/resnet). Stats in f32: bf16 activations would
        # otherwise accumulate in bf16. Centering the pass on a cheap
        # per-channel pivot (spatial mean of batch element 0, ~m within
        # a few std) keeps E[(x-p)^2] - (m-p)^2 far from the
        # catastrophic cancellation the naive E[x^2] - m^2 form hits
        # when |mean| >> std; the pivot slice is 1/N of the data so the
        # extra reduction is noise.
        reduce_axes = tuple(i for i in range(v.ndim) if i != ch)
        n = 1.0
        for i in reduce_axes:
            n *= v.shape[i]
        # the pivot averages two independently-sliced subsamples (all of
        # sample 0, and position 0 of every sample) so that no single
        # pathological slice — a blank first image, a letterboxed corner
        # — can leave the pivot far from the true mean on its own
        x0 = lax.index_in_dim(v, 0, axis=0, keepdims=True).astype(stat_dt)
        p_a = jnp.mean(x0, axis=reduce_axes)           # [C]
        xs = v
        for ax in reduce_axes:
            if ax != 0:
                xs = lax.index_in_dim(xs, 0, axis=ax, keepdims=True)
        p_b = jnp.mean(xs.astype(stat_dt), axis=reduce_axes)   # [C]
        pivot = lax.stop_gradient(0.5 * (p_a + p_b))
        xc = v.astype(stat_dt) - pivot.reshape(shape)
        s1 = jnp.sum(xc, axis=reduce_axes)
        s2 = jnp.sum(xc * xc, axis=reduce_axes)
        d = s1 / n                                     # m - pivot
        var = jnp.maximum(s2 / n - d * d, 0.0)
        m = d + pivot
        new_rm = momentum * rm + (1 - momentum) * m.astype(rm.dtype)
        new_rv = momentum * rv + (1 - momentum) * var.astype(rv.dtype)
        inv = lax.rsqrt(var + epsilon)
    else:
        new_rm, new_rv = rm, rv
        m = jnp.asarray(rm, stat_dt)
        inv = lax.rsqrt(jnp.asarray(rv, stat_dt) + epsilon)
    # explicit centering (x - m) * scale + bias: one fused elementwise
    # pass, and the subtraction happens at activation magnitude so a
    # large channel mean never rounds into the O(1) normalized output
    # (a folded x*scale+shift would put ~|mean|*inv-sized terms on both
    # sides of the add). The apply runs in the INPUT dtype with the [C]
    # vectors cast down — for bf16 activations the information below
    # bf16 resolution is already gone at the input, and an f32 apply
    # chain would force the shared f32 materialization described above.
    scale = inv
    bias = None
    if wb:
        scale = inv * jnp.asarray(wb[0], stat_dt)
        if len(wb) > 1:
            bias = jnp.asarray(wb[1], stat_dt)
    adt = v.dtype
    out = (v - m.astype(adt).reshape(shape)) * scale.astype(adt).reshape(shape)
    if bias is not None:
        out = out + bias.astype(adt).reshape(shape)
    return out, lax.stop_gradient(new_rm), lax.stop_gradient(new_rv)


register_op("batch_norm", _batch_norm_raw)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    """ref operators/batch_norm_op.cc. Running stats are explicit op outputs;
    the wrapper writes them back onto the buffer Tensors (captured by
    functional_call and by the static recorder via alias_output)."""
    ch_axis = 1 if data_format in ("NCHW", "NCL", "NCDHW") else -1
    use_batch_stats = training and not use_global_stats
    args = [x, running_mean, running_var]
    if weight is None and bias is not None:
        weight = Tensor(jnp.ones_like(as_array(bias)))   # shift-only affine
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    outs = apply(_batch_norm_raw, tuple(args),
                 {"ch_axis": int(ch_axis), "momentum": float(momentum),
                  "epsilon": float(epsilon),
                  "training": bool(use_batch_stats)}, name="batch_norm")
    y, new_rm, new_rv = outs
    if use_batch_stats:
        rec = state.get_static_recorder()
        if rec is not None:
            rec.alias_output(new_rm, running_mean)
            rec.alias_output(new_rv, running_var)
        running_mean._data = new_rm._data
        running_var._data = new_rv._data
    return y


def _layer_norm_raw(a, *wb, nd=1, epsilon=1e-5):
    axes = tuple(range(a.ndim - nd, a.ndim))
    if os.environ.get("PT_LN_SINGLE_PASS", "").lower() in ("1", "true",
                                                           "yes", "on"):
        # Experimental single-pass stats (same construction as
        # _batch_norm_raw: centered sum + sum-of-squares in one fused
        # sweep, f32 accumulation, first-element pivot, input-dtype
        # apply). OPT-IN until measured: the BN version won on-chip, but
        # the LN A/B window closed with only tunnel-degraded samples
        # (68-70 ms vs the 64-67 ms band), so the proven two-pass path
        # stays the default — the round-3 lesson is that perf defaults
        # need an on-chip number.
        stat_dt = a.dtype if a.dtype == jnp.float64 else jnp.float32
        af = a.astype(stat_dt)
        n = 1.0
        for ax in axes:
            n *= a.shape[ax]
        # pivot = mean of a leading lane-aligned stripe of each row (up
        # to 128 elements per normalized axis), not a single element —
        # one outlier (padding zero, BOS spike) must not leave the
        # pivot |d| >> std and re-open the cancellation this construction
        # avoids (same safeguard idea as _batch_norm_raw's two-subsample
        # pivot)
        idx = tuple(slice(None) if i not in axes
                    else slice(0, min(128, a.shape[i]))
                    for i in range(a.ndim))
        pivot = lax.stop_gradient(
            jnp.mean(af[idx], axis=axes, keepdims=True))
        ac = af - pivot
        s1 = jnp.sum(ac, axis=axes, keepdims=True)
        s2 = jnp.sum(ac * ac, axis=axes, keepdims=True)
        d = s1 / n
        v = jnp.maximum(s2 / n - d * d, 0.0)
        m = (d + pivot).astype(a.dtype)
        rstd = lax.rsqrt(v + epsilon).astype(a.dtype)
        out = (a - m) * rstd
    else:
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * lax.rsqrt(v + epsilon)
    if wb:
        out = out * wb[0]
        if len(wb) > 1:
            out = out + wb[1]
    return out


register_op("layer_norm", _layer_norm_raw)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, numbers.Number):
        normalized_shape = (normalized_shape,)
    nd = len(tuple(normalized_shape))
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply(_layer_norm_raw, tuple(args),
                 {"nd": nd, "epsilon": float(epsilon)}, name="layer_norm")


def _instance_norm_raw(a, *wb, eps=1e-5):
    axes = tuple(range(2, a.ndim))
    m = jnp.mean(a, axis=axes, keepdims=True)
    v = jnp.var(a, axis=axes, keepdims=True)
    out = (a - m) * lax.rsqrt(v + eps)
    if wb:
        shape = (1, -1) + (1,) * (a.ndim - 2)
        out = out * wb[0].reshape(shape)
        if len(wb) > 1:
            out = out + wb[1].reshape(shape)
    return out


register_op("instance_norm", _instance_norm_raw)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply(_instance_norm_raw, tuple(args), {"eps": float(eps)},
                 name="instance_norm")


def _group_norm_raw(a, *wb, num_groups=1, epsilon=1e-5):
    n, c = a.shape[0], a.shape[1]
    g = num_groups
    r = a.reshape((n, g, c // g) + a.shape[2:])
    axes = tuple(range(2, r.ndim))
    m = jnp.mean(r, axis=axes, keepdims=True)
    v = jnp.var(r, axis=axes, keepdims=True)
    out = ((r - m) * lax.rsqrt(v + epsilon)).reshape(a.shape)
    if wb:
        shape = (1, c) + (1,) * (a.ndim - 2)
        out = out * wb[0].reshape(shape)
        if len(wb) > 1:
            out = out + wb[1].reshape(shape)
    return out


register_op("group_norm", _group_norm_raw)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return apply(_group_norm_raw, tuple(args),
                 {"num_groups": int(num_groups), "epsilon": float(epsilon)},
                 name="group_norm")


def _normalize_raw(a, p=2, axis=1, epsilon=1e-12):
    nrm = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis,
                            keepdims=True), 1.0 / p)
    return a / jnp.maximum(nrm, epsilon)


register_op("normalize", _normalize_raw)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return apply(_normalize_raw, (x,),
                 {"p": float(p), "axis": int(axis), "epsilon": float(epsilon)},
                 name="normalize")


def _local_response_norm_raw(a, size=5, alpha=1e-4, beta=0.75, k=1.0):
    sq = jnp.square(a)
    half = size // 2
    pad_cfg = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2)
    padded = jnp.pad(sq, pad_cfg)
    window = sum(padded[:, i:i + a.shape[1]] for i in range(size))
    return a / jnp.power(k + alpha * window, beta)


register_op("local_response_norm", _local_response_norm_raw)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    return apply(_local_response_norm_raw, (x,),
                 {"size": int(size), "alpha": float(alpha),
                  "beta": float(beta), "k": float(k)},
                 name="local_response_norm")


# ----------------------------------------------------------------- losses

def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    """ref operators/softmax_with_cross_entropy_op.cc — fused log_softmax + NLL."""
    args = (input, label) if weight is None else (input, label, weight)
    return apply(_cross_entropy_raw, args,
                 {"ignore_index": int(ignore_index), "reduction": reduction,
                  "soft_label": bool(soft_label), "axis": int(axis),
                  "use_softmax": bool(use_softmax)}, name="cross_entropy")


def _cross_entropy_raw(logits, lab, *maybe_w, ignore_index=-100,
                       reduction="mean", soft_label=False, axis=-1,
                       use_softmax=True):
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(logits, 1e-30))
    if soft_label:
        per = -jnp.sum(lab * logp, axis=axis)
    else:
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == logp.ndim:  # [N,1] style labels
            lab_i = jnp.squeeze(lab_i, axis=axis)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        per = -jnp.take_along_axis(logp, jnp.expand_dims(safe, axis),
                                   axis=axis)
        per = jnp.squeeze(per, axis=axis)
        if maybe_w:
            w = jnp.take(maybe_w[0], safe)
            per = per * w
        per = jnp.where(valid, per, 0.0)
        if reduction == "mean":
            if maybe_w:
                w = jnp.take(maybe_w[0], safe)
                denom = jnp.sum(jnp.where(valid, w, 0.0))
            else:
                denom = jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
            return jnp.sum(per) / denom
    if reduction == "mean":
        return jnp.mean(per)
    if reduction == "sum":
        return jnp.sum(per)
    return per


register_op("cross_entropy", _cross_entropy_raw)


softmax_with_cross_entropy = cross_entropy


def _reduce_loss(per, reduction):
    if reduction == "mean":
        return jnp.mean(per)
    if reduction == "sum":
        return jnp.sum(per)
    return per


def _nll_loss_raw(logp, lab, *maybe_w, ignore_index=-100, reduction="mean"):
    lab_i = lab.astype(jnp.int32)
    valid = lab_i != ignore_index
    safe = jnp.where(valid, lab_i, 0)
    per = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    if maybe_w:
        per = per * jnp.take(maybe_w[0], safe)
    per = jnp.where(valid, per, 0.0)
    if reduction == "mean":
        denom = (jnp.sum(jnp.take(maybe_w[0], safe) * valid) if maybe_w
                 else jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0))
        return jnp.sum(per) / denom
    return _reduce_loss(per, reduction)


register_op("nll_loss", _nll_loss_raw)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    args = (input, label) if weight is None else (input, label, weight)
    return apply(_nll_loss_raw, args,
                 {"ignore_index": int(ignore_index),
                  "reduction": str(reduction)}, name="nll_loss")


def _mse_loss_raw(a, b, reduction="mean"):
    return _reduce_loss(jnp.square(a - b), reduction)


def _l1_loss_raw(a, b, reduction="mean"):
    return _reduce_loss(jnp.abs(a - b), reduction)


def _smooth_l1_loss_raw(a, b, reduction="mean", delta=1.0):
    d = jnp.abs(a - b)
    l = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce_loss(l, reduction)


register_op("mse_loss", _mse_loss_raw)
register_op("l1_loss", _l1_loss_raw)
register_op("smooth_l1_loss", _smooth_l1_loss_raw)


def mse_loss(input, label, reduction="mean", name=None):
    return apply(_mse_loss_raw, (input, label),
                 {"reduction": str(reduction)}, name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(_l1_loss_raw, (input, label),
                 {"reduction": str(reduction)}, name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return apply(_smooth_l1_loss_raw, (input, label),
                 {"reduction": str(reduction), "delta": float(delta)},
                 name="smooth_l1_loss")


def _binary_cross_entropy_raw(p, y, *maybe_w, reduction="mean"):
    per = -(y * jnp.log(jnp.maximum(p, 1e-12))
            + (1 - y) * jnp.log(jnp.maximum(1 - p, 1e-12)))
    if maybe_w:
        per = per * maybe_w[0]
    return _reduce_loss(per, reduction)


register_op("binary_cross_entropy", _binary_cross_entropy_raw)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    args = (input, label) if weight is None else (input, label, weight)
    return apply(_binary_cross_entropy_raw, args,
                 {"reduction": str(reduction)}, name="binary_cross_entropy")


def _bce_with_logits_raw(z, y, *rest, has_weight=False, has_pos_weight=False,
                         reduction="mean"):
    i = 0
    w = rest[i] if has_weight else None
    if has_weight:
        i += 1
    pw = rest[i] if has_pos_weight else None
    # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
    per = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    if pw is not None:
        log_w = (pw - 1) * y + 1
        per = per * log_w
    if w is not None:
        per = per * w
    return _reduce_loss(per, reduction)


register_op("bce_with_logits", _bce_with_logits_raw)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply(_bce_with_logits_raw, tuple(args),
                 {"has_weight": weight is not None,
                  "has_pos_weight": pos_weight is not None,
                  "reduction": str(reduction)}, name="bce_with_logits")


def _kl_div_raw(logp, y, reduction="mean"):
    per = y * (jnp.log(jnp.maximum(y, 1e-12)) - logp)
    if reduction == "batchmean":
        return jnp.sum(per) / logp.shape[0]
    return _reduce_loss(per, reduction)


register_op("kl_div", _kl_div_raw)


def kl_div(input, label, reduction="mean", name=None):
    return apply(_kl_div_raw, (input, label),
                 {"reduction": str(reduction)}, name="kl_div")


def _margin_ranking_loss_raw(a, b, y, margin=0.0, reduction="mean"):
    per = jnp.maximum(-y * (a - b) + margin, 0.0)
    return _reduce_loss(per, reduction)


register_op("margin_ranking_loss", _margin_ranking_loss_raw)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return apply(_margin_ranking_loss_raw, (input, other, label),
                 {"margin": float(margin), "reduction": str(reduction)},
                 name="margin_ranking_loss")


def _hinge_embedding_loss_raw(a, y, margin=1.0, reduction="mean"):
    per = jnp.where(y == 1, a, jnp.maximum(margin - a, 0.0))
    return _reduce_loss(per, reduction)


register_op("hinge_embedding_loss", _hinge_embedding_loss_raw)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply(_hinge_embedding_loss_raw, (input, label),
                 {"margin": float(margin), "reduction": str(reduction)},
                 name="hinge_embedding_loss")


def _cosine_similarity_raw(a, b, axis=1, eps=1e-8):
    num = jnp.sum(a * b, axis=axis)
    den = jnp.maximum(jnp.linalg.norm(a, axis=axis)
                      * jnp.linalg.norm(b, axis=axis), eps)
    return num / den


register_op("cosine_similarity", _cosine_similarity_raw)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    return apply(_cosine_similarity_raw, (x1, x2),
                 {"axis": int(axis), "eps": float(eps)},
                 name="cosine_similarity")


def _square_error_cost_raw(a, b):
    return jnp.square(a - b)


register_op("square_error_cost", _square_error_cost_raw)


def square_error_cost(input, label):
    return apply(_square_error_cost_raw, (input, label),
                 name="square_error_cost")


def _sigmoid_focal_loss_raw(z, y, *maybe_n, alpha=0.25, gamma=2.0,
                            reduction="sum"):
    p = jax.nn.sigmoid(z)
    ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    p_t = p * y + (1 - p) * (1 - y)
    a_t = alpha * y + (1 - alpha) * (1 - y)
    per = a_t * jnp.power(1 - p_t, gamma) * ce
    if maybe_n:
        per = per / maybe_n[0]
    return _reduce_loss(per, reduction)


register_op("sigmoid_focal_loss", _sigmoid_focal_loss_raw)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    args = (logit, label) if normalizer is None else (logit, label, normalizer)
    return apply(_sigmoid_focal_loss_raw, args,
                 {"alpha": float(alpha), "gamma": float(gamma),
                  "reduction": str(reduction)}, name="sigmoid_focal_loss")


# ----------------------------------------------------------------- padding etc.

def _pad_raw(a, pad=(), mode="constant", value=0.0, channels_first=True):
    p = [int(v) for v in pad]
    if len(p) == 2 * a.ndim:
        cfg = [(p[2 * i], p[2 * i + 1]) for i in range(a.ndim)]
    else:
        # paddle: pad applies to last len(p)//2 spatial dims
        # for NCHW 4-d input with 4 pads: [left,right,top,bottom] on W,H
        n_spatial = len(p) // 2
        cfg = [(0, 0)] * a.ndim
        if channels_first:
            dims = list(range(a.ndim - n_spatial, a.ndim))
        else:
            dims = list(range(1, 1 + n_spatial))
        # paddle order: innermost (last) dim first
        for i, d in enumerate(reversed(dims)):
            cfg[d] = (p[2 * i], p[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(a, cfg, mode="constant", constant_values=value)
    return jnp.pad(a, cfg, mode=jmode)


register_op("pad", _pad_raw)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    return apply(_pad_raw, (x,),
                 {"pad": [int(v) for v in pad], "mode": str(mode),
                  "value": float(value),
                  "channels_first": data_format.startswith("NC")}, name="pad")


def _unfold_raw(a, k=(1, 1), s=(1, 1), p=(0, 0), d=(1, 1)):
    k, s, p, d = (tuple(v) for v in (k, s, p, d))
    n, c, h, w = a.shape
    patches = lax.conv_general_dilated_patches(
        a, filter_shape=k, window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1])], rhs_dilation=d,
        dimension_numbers=lax.conv_dimension_numbers(
            a.shape, (c, c, k[0], k[1]), ("NCHW", "OIHW", "NCHW")))
    # -> [N, C*kh*kw, L]
    return patches.reshape(n, c * k[0] * k[1], -1)


register_op("unfold", _unfold_raw)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    return apply(_unfold_raw, (x,),
                 {"k": list(_norm_tuple(kernel_sizes, 2)),
                  "s": list(_norm_tuple(strides, 2)),
                  "p": list(_norm_tuple(paddings, 2)),
                  "d": list(_norm_tuple(dilations, 2))}, name="unfold")


def _interp_axis_coords(out_n, in_n, align_corners, align_mode=0):
    """Source coordinates for each output index along one axis.
    align_corners=True maps endpoints to endpoints (ref interpolate_op.h
    align_corners branch; ratio 0 when out_n <= 1, selecting pixel 0);
    False uses half-pixel centers when align_mode=0, or the fluid
    asymmetric rule src = i * in/out when align_mode=1 (the reference's
    `align_flag = align_mode == 0 && !align_corners` gate — the default
    for the 1.x resize_bilinear/resize_trilinear builders)."""
    if align_corners:
        ratio = (in_n - 1) / (out_n - 1) if out_n > 1 else 0.0
        return jnp.arange(out_n) * ratio
    scale = in_n / out_n
    if align_mode == 1:
        return jnp.arange(out_n) * scale
    return jnp.maximum((jnp.arange(out_n) + 0.5) * scale - 0.5, 0.0)


def _interp_linear_1axis(a, axis, out_n, align_corners, align_mode=0):
    """Linear resample of one axis by gather + lerp (any rank)."""
    in_n = a.shape[axis]
    c = _interp_axis_coords(out_n, in_n, align_corners, align_mode)
    lo = jnp.clip(jnp.floor(c).astype(jnp.int32), 0, in_n - 1)
    hi = jnp.clip(lo + 1, 0, in_n - 1)
    w = (c - lo).astype(a.dtype)
    lo_v = jnp.take(a, lo, axis=axis)
    hi_v = jnp.take(a, hi, axis=axis)
    shape = [1] * a.ndim
    shape[axis] = out_n
    return lo_v * (1.0 - w.reshape(shape)) + hi_v * w.reshape(shape)


def _interp_nearest_1axis(a, axis, out_n, align_corners):
    """Reference nearest_interp index rule (ref interpolate_op.h
    NearestNeighborInterpolate): floor(i*in/out) without align,
    floor(i*ratio + 0.5) with align_corners."""
    in_n = a.shape[axis]
    i = jnp.arange(out_n)
    if align_corners:
        ratio = (in_n - 1) / (out_n - 1) if out_n > 1 else 0.0
        idx = jnp.floor(i * ratio + 0.5)
    else:
        idx = jnp.floor(i * (in_n / out_n))
    return jnp.take(a, jnp.clip(idx.astype(jnp.int32), 0, in_n - 1),
                    axis=axis)


def _interp_cubic_1axis(a, axis, out_n, align_corners):
    """Cubic (Keys a=-0.75) resample of one axis with 4-tap gathers —
    honors align_corners, unlike jax.image.resize (ref bicubic_interp's
    cubic_interp1d)."""
    in_n = a.shape[axis]
    if align_corners:
        ratio = (in_n - 1) / (out_n - 1) if out_n > 1 else 0.0
        c = jnp.arange(out_n) * ratio
    else:
        # unclamped half-pixel coords: the reference (and torch) only clamp
        # for the linear family; cubic keeps negative fractions at borders
        c = (jnp.arange(out_n) + 0.5) * (in_n / out_n) - 0.5
    base = jnp.floor(c).astype(jnp.int32)
    t = (c - base).astype(a.dtype)
    A = -0.75

    def k1(x):      # |x| <= 1
        return ((A + 2.0) * x - (A + 3.0)) * x * x + 1.0

    def k2(x):      # 1 < |x| < 2
        return ((A * x - 5.0 * A) * x + 8.0 * A) * x - 4.0 * A

    ws = [k2(t + 1.0), k1(t), k1(1.0 - t), k2(2.0 - t)]
    shape = [1] * a.ndim
    shape[axis] = out_n
    out = None
    for tap, w in zip((-1, 0, 1, 2), ws):
        v = jnp.take(a, jnp.clip(base + tap, 0, in_n - 1), axis=axis)
        term = v * w.reshape(shape)
        out = term if out is None else out + term
    return out


def _interpolate_raw(a, size=None, scale_factor=None, mode="nearest",
                     channels_last=False, align_corners=False,
                     align_mode=0):
    """All reference interp op families on one raw (ref operators/
    interpolate_op.cc + interpolate_v2: linear [NCW], bilinear/nearest/
    bicubic/area [NCHW], trilinear [NCDHW]); align_corners honored for the
    nearest/linear family via explicit source-grid gathers."""
    n_spatial = a.ndim - 2
    sp_axes = tuple(range(1, 1 + n_spatial)) if channels_last \
        else tuple(range(2, 2 + n_spatial))
    spatial = tuple(a.shape[ax] for ax in sp_axes)
    if size is not None:
        out_sp = tuple(int(v) for v in (
            size if isinstance(size, (list, tuple)) else [size] * n_spatial))
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else (scale_factor,) * n_spatial
        out_sp = tuple(int(s * f) for s, f in zip(spatial, sf))
    if mode in ("linear", "bilinear", "trilinear"):
        out = a
        for ax, o in zip(sp_axes, out_sp):
            out = _interp_linear_1axis(out, ax, o, align_corners,
                                       align_mode)
        return out
    if mode == "nearest":
        out = a
        for ax, o in zip(sp_axes, out_sp):
            out = _interp_nearest_1axis(out, ax, o, align_corners)
        return out
    if mode == "bicubic":
        out = a
        for ax, o in zip(sp_axes, out_sp):
            out = _interp_cubic_1axis(out, ax, o, align_corners)
        return out
    # area: jax.image.resize antialiased linear (half-pixel semantics)
    shape = list(a.shape)
    for ax, o in zip(sp_axes, out_sp):
        shape[ax] = o
    return jax.image.resize(a, tuple(shape), method="linear")


register_op("interpolate", _interpolate_raw)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    nd = as_array(x).ndim - 2
    if size is not None:
        size = [int(v) for v in
                (size.tolist() if isinstance(size, Tensor) else size)] \
            if not isinstance(size, numbers.Number) else [int(size)] * nd
    if isinstance(scale_factor, (list, tuple)):
        scale_factor = [float(v) for v in scale_factor]
    elif scale_factor is not None:
        scale_factor = float(scale_factor)
    return apply(_interpolate_raw, (x,),
                 {"size": size, "scale_factor": scale_factor,
                  "mode": str(mode),
                  "channels_last": data_format in ("NHWC", "NWC", "NDHWC"),
                  "align_corners": bool(align_corners),
                  "align_mode": int(align_mode)},
                 name="interpolate")


upsample = interpolate


def _pixel_shuffle_raw(a, r=1):
    n, c, h, w = a.shape
    oc = c // (r * r)
    out = a.reshape(n, oc, r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3)
    return out.reshape(n, oc, h * r, w * r)


register_op("pixel_shuffle", _pixel_shuffle_raw)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return apply(_pixel_shuffle_raw, (x,), {"r": int(upscale_factor)},
                 name="pixel_shuffle")


def _temporal_shift_raw(a, seg_num=1, shift_ratio=0.25):
    nt, c, h, w = a.shape
    n = nt // seg_num
    r = a.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.concatenate([r[:, 1:, :fold], jnp.zeros_like(r[:, -1:, :fold])],
                           axis=1)
    right = jnp.concatenate([jnp.zeros_like(r[:, :1, fold:2 * fold]),
                             r[:, :-1, fold:2 * fold]], axis=1)
    rest = r[:, :, 2 * fold:]
    return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)


register_op("temporal_shift", _temporal_shift_raw)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return apply(_temporal_shift_raw, (x,),
                 {"seg_num": int(seg_num), "shift_ratio": float(shift_ratio)},
                 name="temporal_shift")


def _grid_sample_raw(a, g, padding_mode="zeros", align_corners=True):
    n, c, h, w = a.shape
    gx = (g[..., 0] + 1) * (w - 1) / 2 if align_corners \
        else ((g[..., 0] + 1) * w - 1) / 2
    gy = (g[..., 1] + 1) * (h - 1) / 2 if align_corners \
        else ((g[..., 1] + 1) * h - 1) / 2
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1

    def sample(yy, xx):
        yy_c = jnp.clip(yy, 0, h - 1)
        xx_c = jnp.clip(xx, 0, w - 1)
        v = a[jnp.arange(n)[:, None, None], :, yy_c, xx_c]  # [N,Hg,Wg,C]
        if padding_mode == "zeros":
            inb = ((yy >= 0) & (yy < h) & (xx >= 0) & (xx < w))[..., None]
            v = jnp.where(inb, v, 0.0)
        return v

    wa = ((x1 - gx) * (y1 - gy))[..., None]
    wb = ((x1 - gx) * (gy - y0))[..., None]
    wc = ((gx - x0) * (y1 - gy))[..., None]
    wd = ((gx - x0) * (gy - y0))[..., None]
    out = (sample(y0, x0) * wa + sample(y1, x0) * wb
           + sample(y0, x1) * wc + sample(y1, x1) * wd)
    return out.transpose(0, 3, 1, 2)


register_op("grid_sample", _grid_sample_raw)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    return apply(_grid_sample_raw, (x, grid),
                 {"padding_mode": str(padding_mode),
                  "align_corners": bool(align_corners)}, name="grid_sample")


def _affine_grid_raw(th, out_shape=(), align_corners=True):
    n, _, h, w = [int(v) for v in out_shape]
    if align_corners:
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1
        xs = (jnp.arange(w) * 2 + 1) / w - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # [H,W,3]
    return jnp.einsum("nij,hwj->nhwi", th, base)


register_op("affine_grid", _affine_grid_raw)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    shape = [int(v) for v in (out_shape.tolist()
                              if isinstance(out_shape, Tensor) else out_shape)]
    return apply(_affine_grid_raw, (theta,),
                 {"out_shape": shape, "align_corners": bool(align_corners)},
                 name="affine_grid")


def _label_smooth_raw(y, *maybe_p, epsilon=0.1):
    k = y.shape[-1]
    if maybe_p:
        return (1 - epsilon) * y + epsilon * maybe_p[0]
    return (1 - epsilon) * y + epsilon / k


register_op("label_smooth", _label_smooth_raw)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    args = (label,) if prior_dist is None else (label, prior_dist)
    return apply(_label_smooth_raw, args, {"epsilon": float(epsilon)},
                 name="label_smooth")


def _npair_loss_raw(a, p, y, l2_reg=0.002):
    sim = jnp.matmul(a, p.T)
    same = (y[:, None] == y[None, :]).astype(a.dtype)
    same = same / jnp.sum(same, axis=1, keepdims=True)
    ce = jnp.mean(-jnp.sum(same * jax.nn.log_softmax(sim, axis=1), axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(a), 1))
                    + jnp.mean(jnp.sum(jnp.square(p), 1))) * 0.25
    return ce + reg


register_op("npair_loss", _npair_loss_raw)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    return apply(_npair_loss_raw, (anchor, positive, labels),
                 {"l2_reg": float(l2_reg)}, name="npair_loss")


def _diag_embed_raw(a):
    out = jnp.zeros(a.shape + (a.shape[-1],), a.dtype)
    idx = jnp.arange(a.shape[-1])
    return out.at[..., idx, idx].set(a)


register_op("diag_embed", _diag_embed_raw)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    return apply(_diag_embed_raw, (x,), name="diag_embed")


def _sequence_mask_raw(l, maxlen=1, out_dtype="int64"):
    return (jnp.arange(maxlen)[None, :] < l[:, None]).astype(
        convert_dtype(out_dtype))


register_op("sequence_mask", _sequence_mask_raw)


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    ml = int(maxlen) if maxlen is not None else int(np.asarray(
        as_array(lengths)).max())
    return apply(_sequence_mask_raw, (lengths,),
                 {"maxlen": ml, "out_dtype": str(dtype)},
                 differentiable=False, name="sequence_mask")


def _pairwise_distance_raw(x_, y_, p=2.0, keepdim=False):
    return jnp.linalg.norm(x_ - y_, ord=p, axis=-1, keepdims=keepdim)


register_op("pairwise_distance", _pairwise_distance_raw)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """ref nn/functional/distance.py: p-norm of (x - y) over the last
    axis. The reference's p_norm kernel uses `epsilon` only in the
    GRADIENT denominator (p_norm_op.h PnormGradKernel), never the
    forward — kept in the signature for API parity; autodiff handles the
    norm-at-zero subgradient here."""
    return apply(_pairwise_distance_raw, (x, y),
                 {"p": float(p), "keepdim": bool(keepdim)},
                 name="pairwise_distance")


def _ctc_loss_raw(lp, lab, in_len, lab_len, blank=0, reduction="mean",
                  norm_by_times=False):
    T, B, C = lp.shape
    Lmax = lab.shape[1]
    S = 2 * Lmax + 1
    logp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
    neg_inf = jnp.float32(-1e30)

    # extended label sequence l' = [blank, l1, blank, l2, ..., blank]
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
    # transition-2 allowed where l'_s != blank and l'_s != l'_{s-2}
    ext_m2 = jnp.concatenate(
        [jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_m2)

    def emit(t_logp):
        # t_logp: [B, C] -> per-extended-position emission [B, S]
        return jnp.take_along_axis(t_logp, ext, axis=1)

    alpha0 = jnp.full((B, S), neg_inf)
    e0 = emit(logp[0])
    alpha0 = alpha0.at[:, 0].set(e0[:, 0])
    if S > 1:      # Lmax=0 (all-blank targets) has only position 0
        alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, e0[:, 1],
                                               neg_inf))

    def step(alpha, t_logp_t):
        t_logp, t = t_logp_t
        if S > 1:
            prev1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            prev2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf),
                 alpha[:, :max(S - 2, 0)]], axis=1)[:, :S]
            prev2 = jnp.where(can_skip, prev2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
        else:      # Lmax=0: only the all-blank path exists
            merged = alpha
        new = merged + emit(t_logp)
        # freeze finished samples (t >= input_length)
        active = (t < in_len)[:, None]
        return jnp.where(active, new, alpha), None

    ts = jnp.arange(1, T)
    alpha, _ = jax.lax.scan(step, alpha0, (logp[1:], ts))

    # final: logsumexp of positions S-1 (last blank) and S-2 (last label)
    s_last = 2 * lab_len.astype(jnp.int32)        # index of last blank
    a_last = jnp.take_along_axis(alpha, s_last[:, None], axis=1)[:, 0]
    s_lab = jnp.maximum(s_last - 1, 0)
    a_lab = jnp.where(
        lab_len > 0,
        jnp.take_along_axis(alpha, s_lab[:, None], axis=1)[:, 0],
        neg_inf)
    nll = -jnp.logaddexp(a_last, a_lab)
    if norm_by_times:
        nll = nll / jnp.maximum(in_len.astype(jnp.float32), 1.0)
    if reduction == "mean":
        # paddle mean: divide per-sample loss by label_length first
        return jnp.mean(nll / jnp.maximum(
            lab_len.astype(jnp.float32), 1.0))
    if reduction == "sum":
        return jnp.sum(nll)
    return nll


register_op("ctc_loss", _ctc_loss_raw)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (ref operators/warpctc_op.cc / paddle.nn.functional.ctc_loss).

    log_probs: [T, B, C] RAW logits (log_softmax applied internally, like
    the reference's warpctc). labels: [B, Lmax] padded int labels.
    input_lengths/label_lengths: [B] ints.

    TPU-native: the alpha recursion runs as one lax.scan over time in log
    space with static shapes ([B, 2*Lmax+1] state); per-sample lengths are
    handled by masking, so one compiled program serves the whole batch.
    Gradients come from autodiff through the scan (the reference ships a
    hand-written backward; XLA differentiates the recursion directly).
    """
    return apply(_ctc_loss_raw,
                 (log_probs, labels, input_lengths, label_lengths),
                 {"blank": int(blank), "reduction": str(reduction),
                  "norm_by_times": bool(norm_by_times)}, name="ctc_loss")


def _gather_tree_raw(ids_, par_):
    T, B, K = ids_.shape
    par_ = par_.astype(jnp.int32)

    def step(beams, xs):
        ids_t, par_t = xs
        out_t = jnp.take_along_axis(ids_t, beams, axis=-1)
        prev = jnp.take_along_axis(par_t, beams, axis=-1)
        return prev, out_t

    init = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32), (B, K))
    _, outs = jax.lax.scan(step, init, (ids_, par_), reverse=True)
    return outs


register_op("gather_tree", _gather_tree_raw)


def gather_tree(ids, parents):
    """Reconstruct full beam-search sequences from per-step token ids and
    parent beam indices (ref operators/gather_tree_op.cc; both [T, B, K]).
    TPU-native: one reverse lax.scan walking the parent chain — no
    per-(batch, beam) host loops."""
    return apply(_gather_tree_raw, (ids, parents), differentiable=False,
                 name="gather_tree")


# --------------------------------------------------------------- round-3 tail
# (last nn.functional gaps vs ref python/paddle/nn/functional: 1d/3d pools,
# 1d/3d transposed convs, log_sigmoid/thresholded_relu, hsigmoid_loss,
# inplace variants)

def _log_sigmoid_raw(a):
    return jax.nn.log_sigmoid(a)


def _thresholded_relu_raw(a, threshold=1.0):
    return jnp.where(a > threshold, a, 0.0)


register_op("log_sigmoid", _log_sigmoid_raw)
register_op("thresholded_relu", _thresholded_relu_raw)


def log_sigmoid(x, name=None):
    return apply(_log_sigmoid_raw, (x,), name="log_sigmoid")


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(_thresholded_relu_raw, (x,),
                 {"threshold": float(threshold)}, name="thresholded_relu")


def _inplace(x, out):
    x._data = out._data
    x._node, x._slot = out._node, out._slot
    return x


def relu_(x, name=None):
    return _inplace(x, relu(x))


def elu_(x, alpha=1.0, name=None):
    return _inplace(x, elu(x, alpha=alpha))


def softmax_(x, axis=-1, dtype=None, name=None):
    return _inplace(x, softmax(x, axis=axis, dtype=dtype))


register_op("max_pool3d", functools.partial(_poolnd_raw, n=3, average=False))
register_op("avg_pool3d", functools.partial(_poolnd_raw, n=3, average=True))


def _reject_pool_extras(data_format, canonical):
    if data_format not in (None, canonical):
        raise NotImplementedError(
            f"pooling: only {canonical} layout supported, got {data_format}")


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    _reject_pool_extras(data_format, "NCDHW")
    # NCDHW validated above = channels-first; _pool owns the attr build
    return _pool(x, kernel_size, stride, padding, "NCHW", "max_pool3d",
                 ceil_mode=ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               count_include_pad=True, divisor_override=None,
               data_format="NCDHW", name=None):
    _reject_pool_extras(data_format, "NCDHW")
    if divisor_override is not None:
        raise NotImplementedError("avg_pool3d: divisor_override unsupported")
    return _pool(x, kernel_size, stride, padding, "NCHW", "avg_pool3d",
                 ceil_mode=ceil_mode, count_include_pad=count_include_pad,
                 average=True)


def _adaptive_poolnd_raw(a, output_size=1, n=2, average=True):
    """Divisible-size adaptive pool for any spatial rank (reshape-reduce)."""
    out_sz = _norm_tuple(output_size, n)
    lead = a.shape[:a.ndim - n]
    spatial = a.shape[a.ndim - n:]
    shape = list(lead)
    red_axes = []
    for i, (s, o) in enumerate(zip(spatial, out_sz)):
        if s % o:
            raise NotImplementedError(
                "adaptive pooling with non-divisible sizes not supported")
        shape += [o, s // o]
        red_axes.append(len(lead) + 2 * i + 1)
    r = a.reshape(shape)
    return (r.mean(axis=tuple(red_axes)) if average
            else r.max(axis=tuple(red_axes)))


register_op("adaptive_avg_pool1d",
            functools.partial(_adaptive_poolnd_raw, n=1, average=True))
register_op("adaptive_max_pool1d",
            functools.partial(_adaptive_poolnd_raw, n=1, average=False))
register_op("adaptive_avg_pool3d",
            functools.partial(_adaptive_poolnd_raw, n=3, average=True))
register_op("adaptive_max_pool3d",
            functools.partial(_adaptive_poolnd_raw, n=3, average=False))


def _adaptive_pool_fn(opname):
    from ..ops.dispatch import OP_REGISTRY

    def fn(x, output_size, name=None, return_mask=False,
           data_format=None):
        if data_format not in (None, "NCL", "NCHW", "NCDHW"):
            raise NotImplementedError(
                f"{opname}: only channels-first layouts supported, "
                f"got {data_format}")
        return apply(OP_REGISTRY[opname], (x,),
                     {"output_size": _stride_attr(output_size)},
                     name=opname)
    fn.__name__ = opname
    return fn


adaptive_avg_pool1d = _adaptive_pool_fn("adaptive_avg_pool1d")
adaptive_max_pool1d = _adaptive_pool_fn("adaptive_max_pool1d")
adaptive_avg_pool3d = _adaptive_pool_fn("adaptive_avg_pool3d")
adaptive_max_pool3d = _adaptive_pool_fn("adaptive_max_pool3d")


def _convnd_transpose_raw(a, w, *maybe_b, n=2, stride=1, padding=0,
                          output_padding=0, dilation=1, groups=1):
    """N-d transposed conv, NCX layout, weight [in_c, out_c/g, *k]
    (generalizes the 2-d path; ref conv_transpose_op.cc)."""
    strides = _norm_tuple(stride, n)
    dilations = _norm_tuple(dilation, n)
    out_pad = _norm_tuple(output_padding, n)
    pad = _conv_padding(padding, n, strides, dilations, w.shape[2:])
    if isinstance(pad, str):
        if pad != "VALID":
            raise ValueError("SAME padding unsupported for conv_transpose")
        pad = [(0, 0)] * n
    keff = [((w.shape[2 + i] - 1) * dilations[i] + 1) for i in range(n)]
    trans_pad = [(keff[i] - 1 - pad[i][0],
                  keff[i] - 1 - pad[i][1] + out_pad[i]) for i in range(n)]
    w_flip = jnp.flip(w, axis=tuple(range(2, 2 + n)))
    spatial = "DHW"[3 - n:]
    dn_str = ("NC" + spatial, "OI" + spatial, "NC" + spatial)
    perm = (1, 0) + tuple(range(2, 2 + n))

    def one(a_g, w_g):
        w_t = jnp.transpose(w_g, perm)       # -> [out_c/g, in_c/g, *k]
        dn = lax.conv_dimension_numbers(a_g.shape, w_t.shape, dn_str)
        return lax.conv_general_dilated(
            a_g, w_t, window_strides=(1,) * n, padding=trans_pad,
            lhs_dilation=strides, rhs_dilation=dilations,
            dimension_numbers=dn)

    if groups == 1:
        out = one(a, w_flip)
    else:
        icg = a.shape[1] // groups
        out = jnp.concatenate(
            [one(a[:, g * icg:(g + 1) * icg],
                 w_flip[g * icg:(g + 1) * icg]) for g in range(groups)],
            axis=1)
    if maybe_b:
        out = out + maybe_b[0].reshape((1, -1) + (1,) * n)
    return out


register_op("conv1d_transpose",
            functools.partial(_convnd_transpose_raw, n=1))
register_op("conv3d_transpose",
            functools.partial(_convnd_transpose_raw, n=3))


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    from ..ops.dispatch import OP_REGISTRY
    if data_format != "NCL":
        raise NotImplementedError(
            f"conv1d_transpose: only NCL supported, got {data_format}")
    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(OP_REGISTRY["conv1d_transpose"], args,
                 {"stride": _stride_attr(stride), "padding": _pad_attr(padding),
                  "output_padding": _stride_attr(output_padding),
                  "dilation": _stride_attr(dilation), "groups": int(groups)},
                 name="conv1d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    from ..ops.dispatch import OP_REGISTRY
    if data_format != "NCDHW":
        raise NotImplementedError(
            f"conv3d_transpose: only NCDHW supported, got {data_format}")
    args = (x, weight) if bias is None else (x, weight, bias)
    return apply(OP_REGISTRY["conv3d_transpose"], args,
                 {"stride": _stride_attr(stride), "padding": _pad_attr(padding),
                  "output_padding": _stride_attr(output_padding),
                  "dilation": _stride_attr(dilation), "groups": int(groups)},
                 name="conv3d_transpose")


def bilinear(x1, x2, weight, bias=None, name=None):
    """ref nn/functional/common.py bilinear: out[b,o] = x1 W_o x2 + b."""
    from ..nn.layers_common import _bilinear_raw
    args = (x1, x2, weight) if bias is None else (x1, x2, weight, bias)
    return apply(_bilinear_raw, args, name="bilinear")


def _hsigmoid_loss_raw(x, lab, w, *maybe_b, num_classes=2):
    """Hierarchical sigmoid over the default COMPLETE binary tree (ref
    hierarchical_sigmoid_op.cc without custom paths): internal nodes are
    1..C-1 heap-style; class c maps to leaf c + (C-1); the loss is the
    sum of binary CE along the root->leaf path. Static shapes: every path
    is padded to ceil(log2(C)) with zero-weight steps."""
    C = num_classes
    depth = max(int(np.ceil(np.log2(max(C, 2)))), 1)
    leaf = lab.reshape(-1).astype(jnp.int32) + (C - 1)   # accepts [N] or [N,1]
    losses = jnp.zeros(x.shape[0], jnp.float32)
    node = leaf
    for _ in range(depth):
        parent = (node - 1) // 2
        is_right = (node % 2 == 0) & (node > 0)
        valid = node > 0
        # internal-node weight row: parent index in [0, C-1)
        row = jnp.clip(parent, 0, C - 2)
        z = jnp.einsum("nd,nd->n", x, w[row])
        if maybe_b:
            z = z + maybe_b[0].reshape(-1)[row]
        t = is_right.astype(jnp.float32)
        bce = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        losses = losses + jnp.where(valid, bce, 0.0)
        node = parent
    return losses[:, None]


register_op("hsigmoid_loss", _hsigmoid_loss_raw)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "hsigmoid_loss: custom path tables not supported (default "
            "complete-binary-tree only)")
    args = (input, label, weight) if bias is None \
        else (input, label, weight, bias)
    return apply(_hsigmoid_loss_raw, args, {"num_classes": int(num_classes)},
                 name="hsigmoid_loss")


def _deform_conv2d_raw(x, offset, w, *rest, stride=1, padding=0, dilation=1,
                       has_mask=False, has_bias=False):
    """Deformable conv v1/v2 (ref operators/deformable_conv_op.h;
    static.nn.deform_conv2d). deformable_groups=1, groups=1.

    x [N,C,H,W]; offset [N, 2*kh*kw, H',W'] as (dy,dx) pairs; w
    [Co,C,kh,kw]; optional mask [N, kh*kw, H',W'] (v2 modulation) and
    bias [Co]. TPU-native: the kernel-offset sampling grid is built
    densely and gathered with ONE take_along_axis per corner — bilinear
    interpolation as four fused gathers, no per-position loops."""
    mask = rest[0] if has_mask else None
    b = rest[-1] if has_bias else None
    n_, c, h, w_in = x.shape
    co, _, kh, kw = w.shape
    sh, sw = _norm_tuple(stride, 2)
    ph, pw = _norm_tuple(padding, 2)
    dh, dw = _norm_tuple(dilation, 2)
    ho = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    wo = (w_in + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    K = kh * kw

    oi = jnp.arange(ho)[:, None]                  # output rows
    oj = jnp.arange(wo)[None, :]
    ku, kv = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
    base_y = (oi * sh - ph)[None, :, :] + (ku.reshape(-1) * dh)[:, None, None]
    base_x = (oj * sw - pw)[None, :, :] + (kv.reshape(-1) * dw)[:, None, None]
    off = offset.reshape(n_, K, 2, ho, wo)
    ys = base_y[None] + off[:, :, 0]              # [N,K,H',W']
    xs = base_x[None] + off[:, :, 1]

    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = ys - y0
    wx = xs - x0

    def gather(yy, xx):
        inb = ((yy >= 0) & (yy < h) & (xx >= 0) & (xx < w_in))
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w_in - 1).astype(jnp.int32)
        flat = (yc * w_in + xc).reshape(n_, 1, -1)        # [N,1,K*H'*W']
        got = jnp.take_along_axis(x.reshape(n_, c, h * w_in), flat, axis=2)
        got = got.reshape(n_, c, K, ho, wo)
        return jnp.where(inb[:, None], got, 0.0)

    sampled = ((1 - wy) * (1 - wx))[:, None] * gather(y0, x0) \
        + ((1 - wy) * wx)[:, None] * gather(y0, x0 + 1) \
        + (wy * (1 - wx))[:, None] * gather(y0 + 1, x0) \
        + (wy * wx)[:, None] * gather(y0 + 1, x0 + 1)     # [N,C,K,H',W']
    if mask is not None:
        sampled = sampled * mask[:, None]
    out = jnp.einsum("nckij,ock->noij", sampled,
                     w.reshape(co, c, K),
                     preferred_element_type=jnp.float32).astype(x.dtype)
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    return out


register_op("deform_conv2d", _deform_conv2d_raw)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    if deformable_groups != 1 or groups != 1:
        raise NotImplementedError(
            "deform_conv2d: deformable_groups/groups > 1 unsupported")
    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply(_deform_conv2d_raw, tuple(args),
                 {"stride": _stride_attr(stride), "padding": _pad_attr(padding),
                  "dilation": _stride_attr(dilation),
                  "has_mask": mask is not None, "has_bias": bias is not None},
                 name="deform_conv2d")
