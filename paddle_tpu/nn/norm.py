"""Norm layers (ref python/paddle/nn/layer/norm.py): BatchNorm1D/2D/3D, LayerNorm,
GroupNorm, InstanceNorm, SyncBatchNorm (on TPU SyncBatchNorm = BatchNorm whose
stats are psum'd across the data axis when running under shard_map)."""
import numbers

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, weight=self.weight, bias=self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (ref fluid/dygraph/nn.py BatchNorm) — acts like
    the 2.0 BatchNorm but accepts act."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, use_global_stats=False,
                 trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit/GSPMD the batch axis is globally visible to
    XLA, so plain batch statistics ARE global statistics — the reference's
    NCCL sync kernel (ref operators/sync_batch_norm_op.cu) is unnecessary."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer,
                                                                SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            if layer.weight is not None:
                new.weight.set_value(layer.weight)
            if layer.bias is not None:
                new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, numbers.Number):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self._normalized_shape,
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, weight=self.weight,
                            bias=self.bias, epsilon=self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, epsilon=self._epsilon,
                            weight=self.weight, bias=self.bias)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self._dim, self._power_iters, self._eps = dim, power_iters, eps
        import numpy as np
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0, 1))
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ..ops.dispatch import apply
        from ..framework.tensor import Tensor as _T
        dim, iters, eps = self._dim, self._power_iters, self._eps
        # eager calls ADVANCE the persisted power-iteration state (ref
        # spectral_norm_op: u/v updated every call, so sigma converges
        # across steps); under tracing the state is read-only
        warr = weight._data if isinstance(weight, _T) else weight
        if not isinstance(warr, jax.core.Tracer):
            wm_ = jnp.moveaxis(warr, dim, 0).reshape(warr.shape[dim], -1)
            u_, v_ = self.weight_u._data, self.weight_v._data
            for _ in range(iters):
                v_ = wm_.T @ u_
                v_ = v_ / (jnp.linalg.norm(v_) + eps)
                u_ = wm_ @ v_
                u_ = u_ / (jnp.linalg.norm(u_) + eps)
            self.weight_u._data = u_
            self.weight_v._data = v_
        u0, v0 = self.weight_u._data, self.weight_v._data

        def f(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma
        return apply(f, (weight,), name="spectral_norm")
