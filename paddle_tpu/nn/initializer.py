"""Weight initializers (ref python/paddle/fluid/initializer.py: Constant, Uniform,
Normal, TruncatedNormal, Xavier, MSRA/Kaiming, Bilinear, Assign).

Each initializer is a callable (shape, dtype) -> jnp.ndarray drawing from the
global Generator chain."""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import state
from ..framework.dtype import convert_dtype


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *spatial] (NCHW weights)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    """Initialisation runs on host CPU: weight init is latency-bound
    bookkeeping, not MXU work, and on tunneled TPUs each eager op is a network
    round-trip. The arrays migrate to the accelerator on first real use
    (jit input placement / device_put in the train-step compilers).

    Subclasses implement `_generate(shape, dtype)`; `__call__` is the template
    method that pins the computation to the host device."""

    def __call__(self, shape, dtype="float32"):
        from ..framework.state import host_device
        with jax.default_device(host_device()):
            return self._generate(shape, dtype)

    def _generate(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, convert_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def _generate(self, shape, dtype):
        return jax.random.uniform(state.next_rng_key(), tuple(shape),
                                  convert_dtype(dtype), self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        return (jax.random.normal(state.next_rng_key(), tuple(shape),
                                  convert_dtype(dtype)) * self.std + self.mean)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        return (jax.random.truncated_normal(state.next_rng_key(), -2.0, 2.0,
                                            tuple(shape), convert_dtype(dtype))
                * self.std + self.mean)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(state.next_rng_key(), tuple(shape),
                                  convert_dtype(dtype), -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(state.next_rng_key(), tuple(shape),
                                 convert_dtype(dtype)) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in

    def _generate(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        limit = math.sqrt(6.0 / fi)
        return jax.random.uniform(state.next_rng_key(), tuple(shape),
                                  convert_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in

    def _generate(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        std = math.sqrt(2.0 / fi)
        return jax.random.normal(state.next_rng_key(), tuple(shape),
                                 convert_dtype(dtype)) * std


MSRAInitializer = KaimingNormal


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def _generate(self, shape, dtype):
        arr = np.asarray(self.value)
        return jnp.asarray(arr, convert_dtype(dtype)).reshape(tuple(shape))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def _generate(self, shape, dtype):
        return jax.nn.initializers.orthogonal(scale=self.gain)(
            state.next_rng_key(), tuple(shape), convert_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def _generate(self, shape, dtype):
        out = np.zeros(tuple(shape), dtype=np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic)):
            out[(i, i) + tuple(centers)] = 1.0
        return jnp.asarray(out, convert_dtype(dtype))


# reference-compat aliases (fluid.initializer names)
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = XavierNormal
NumpyArrayInitializer = Assign


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 0.75}
    return gains[nonlinearity]


class Bilinear(Initializer):
    """ref initializer.py BilinearInitializer — transposed-conv upsampling
    kernels: EVERY channel pair of the 4-D weight gets the separable
    bilinear interpolation filter (the reference fills all channels, so
    the canonical grouped layout [C, 1, kh, kw] upsamples every channel)."""

    def _generate(self, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear expects a 4-D conv weight shape")
        kh, kw = shape[2], shape[3]

        def filt(k):
            f = (k + 1) // 2
            center = f - 1 if k % 2 == 1 else f - 0.5
            return (1 - np.abs(np.arange(k) - center) / f)

        kern = np.outer(filt(kh), filt(kw))
        w = np.broadcast_to(kern, shape)
        return jnp.asarray(w, convert_dtype(dtype))
