"""Fused paged-attention kernel family: gather + attend over the block
pools in one pass.

The paged serving paths (decode wave, spec draft wave, spec verify,
prefill chunk) historically read the KV cache in two steps:
`gather_block_kv` materialised a `[B, Hkv, nblk*BS, D]` copy of every
lane's blocks, then `cached_decode_attention`/`chunk_attention`
consumed it. That intermediate is a full extra HBM round-trip over the
cache per layer per wave — exactly the memory-intensive op class the
operator-fusion literature (PAPERS.md: "Operator Fusion in XLA",
"FusionStitching") shows XLA's default fusion will not stitch away.

This module replaces the pair with kernels that read K/V *directly out
of the per-layer block pool through the block table* using an online
(streaming) softmax over blocks — the `[B, Hkv, nblk*BS, D]` gathered
view never exists. Three interchangeable implementations sit behind one
dispatch point:

  kernel="reference"  the original gather-then-attend pair, kept as the
                      selectable parity oracle (bitwise the pre-fusion
                      program);
  kernel="lax"        a lax.fori_loop over blocks carrying the
                      flash-attention recurrence (running max m, denom
                      l, weighted accumulator); works on every backend;
  kernel="pallas"     a Pallas TPU kernel — grid over (lanes, kv-heads,
                      blocks), the block-table gather done by the
                      BlockSpec index_map over a scalar-prefetch table,
                      accumulators in VMEM scratch across the
                      sequential block dimension. `interpret=True` on
                      CPU so tier-1 exercises the real kernel body.
  kernel="auto"       "pallas" on TPU, "lax" elsewhere.

Both serving attention shapes are covered: the decode form (one query
per lane; replaces gather+`cached_decode_attention` in the decode and
spec-draft waves) and the chunked form (C queries at per-lane offsets;
replaces gather+`chunk_attention` in `prefill_chunk` and the spec
verify wave). Decode is the C == 1 case of the chunk recurrence, but
keeps its own entry point so the xprof registry can track the two cores
as distinct programs.

Masking contract (the `-1e9` wart fixed): masked/out-of-window scores
are hard-excluded with `-inf` *before* the max/exp, and fully-masked
rows (all-scratch lanes, padded chunk tails) renormalise through a
guarded `where(l == 0, 0, acc / l)` instead of softmaxing over a
uniform `-1e9` row. Scratch-block garbage — which may be non-finite — therefore
cannot reach the engines' isfinite poison sentinel, while a genuine
non-finite value at any *attended* position still propagates to the
logits exactly as before.

Dispatch resolution order for kernel=None: the innermost active
`kernel_scope(...)` (how the serving engines pin the kernel they were
built with at trace time) > the `PT_PAGED_KERNEL` environment variable
> the module default from `set_paged_kernel` > "auto".
"""
import contextlib
import functools
import os

KERNELS = ("auto", "reference", "lax", "pallas")

_DEFAULT_KERNEL = "auto"
_SCOPE_STACK = []           # innermost kernel_scope override, LIFO


def set_paged_kernel(kernel):
    """Set the process-wide default paged-attention kernel."""
    global _DEFAULT_KERNEL
    _DEFAULT_KERNEL = _check(kernel)


def get_paged_kernel():
    """The unresolved process default (may be "auto")."""
    return _DEFAULT_KERNEL


def _check(kernel):
    if kernel not in KERNELS:
        raise ValueError(f"unknown paged kernel {kernel!r}: "
                         f"expected one of {KERNELS}")
    return kernel


@contextlib.contextmanager
def kernel_scope(kernel):
    """Pin the kernel inside a `with` block. The serving engines trace
    their jitted programs inside this scope, so the engine's configured
    kernel wins over the process default no matter which thread or
    engine traced first (tracing runs the Python body; the compiled
    program keeps whatever the scope resolved)."""
    _SCOPE_STACK.append(_check(kernel))
    try:
        yield
    finally:
        _SCOPE_STACK.pop()


def resolve_kernel(kernel=None):
    """Resolve to a concrete implementation name ("reference" | "lax" |
    "pallas"). Resolution order: explicit argument > innermost
    kernel_scope > PT_PAGED_KERNEL env > set_paged_kernel default; an
    "auto" at any level falls through to backend selection (pallas on
    TPU, lax elsewhere)."""
    choice = None
    if kernel is not None:
        choice = _check(kernel)
    elif _SCOPE_STACK:
        choice = _SCOPE_STACK[-1]
    else:
        env = os.environ.get("PT_PAGED_KERNEL", "").strip().lower()
        if env:
            choice = _check(env)
        else:
            choice = _DEFAULT_KERNEL
    if choice != "auto":
        return choice
    import jax
    return "pallas" if jax.default_backend() == "tpu" else "lax"


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

def paged_decode_attention(q, pk, pv, tables, pos, scale, window=None,
                           kernel=None):
    """Fused decode attention over the block pool. q: [B, H, 1, D];
    pk/pv: [NB, Hkv, BS, D] pools; tables: [B, nblk] int32; pos a traced
    scalar or [B] vector of each lane's current position (the query's
    own absolute position — keys at ks <= pos are attended, banded to
    the last `window` when given). Returns [B, H, 1, D] in pv.dtype.

    Equivalent to gather_block_kv + cached_decode_attention without the
    gathered [B, Hkv, nblk*BS, D] intermediate."""
    k = resolve_kernel(kernel)
    if k == "reference":
        from .transformer import cached_decode_attention, gather_block_kv
        # sanitize: the gathered view contains scratch-block positions
        # (masked by construction) whose garbage may be non-finite
        return cached_decode_attention(q, gather_block_kv(pk, tables),
                                       gather_block_kv(pv, tables),
                                       pos, scale, window=window,
                                       sanitize=True)
    if k == "pallas":
        return _pallas_core(q, pk, pv, tables, pos, scale, window)
    return _lax_core(q, pk, pv, tables, pos, scale, window)


def paged_chunk_attention(q, pk, pv, tables, start, scale, window=None,
                          kernel=None):
    """Fused chunk attention over the block pool: C queries per lane at
    absolute positions start + i (start: traced scalar or [B] vector).
    q: [B, H, C, D]; pools/tables as in paged_decode_attention. Query
    row i masks ks <= start + i (banded to the last `window` keys when
    given). Returns [B, H, C, D] in pv.dtype.

    Equivalent to gather_block_kv + chunk_attention without the
    gathered intermediate; the decode form is the C == 1 case."""
    k = resolve_kernel(kernel)
    if k == "reference":
        from .transformer import chunk_attention, gather_block_kv
        return chunk_attention(q, gather_block_kv(pk, tables),
                               gather_block_kv(pv, tables),
                               start, scale, window=window,
                               sanitize=True)
    if k == "pallas":
        return _pallas_core(q, pk, pv, tables, start, scale, window)
    return _lax_core(q, pk, pv, tables, start, scale, window)


def _query_positions(start, b, c):
    """[B, C] int32 absolute position of every query row from a traced
    scalar or [B] start vector."""
    import jax.numpy as jnp
    qpos = jnp.reshape(jnp.asarray(start), (-1, 1)) + jnp.arange(c)
    return jnp.broadcast_to(qpos, (b, c)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# lax fallback: fori_loop over blocks, flash-attention recurrence
# ---------------------------------------------------------------------------

def _lax_core(q, pk, pv, tables, start, scale, window=None):
    """Online-softmax attention streamed block-by-block out of the pool.

    Carries (m, l, acc) across the nblk sequential steps: per block j
    the lane's j-th pool block is fetched ([B, Hkv, BS, D] — the only
    gathered working set that ever exists), scored against the queries,
    masked with -inf at ks > qpos (and outside the window), and folded
    into the running max/denominator/weighted-V with the standard
    rescale alpha = exp(m_old - m_new). Fully-masked rows finish with
    l == 0 and renormalise to exactly 0 via the guarded `where` — never
    an average over scratch garbage."""
    import jax
    import jax.numpy as jnp

    b, h, c, d = q.shape
    hkv, bs = pk.shape[1], pk.shape[2]
    nblk = tables.shape[1]
    rep = h // hkv
    qf = q.astype(jnp.float32).reshape(b, hkv, rep, c, d)
    qpos = _query_positions(start, b, c)               # [B, C]
    neg_inf = jnp.float32(-jnp.inf)

    def body(j, carry):
        m, l, acc = carry
        blk = tables[:, j]                             # [B]
        kblk = pk[blk].astype(jnp.float32)             # [B, Hkv, BS, D]
        vblk = pv[blk].astype(jnp.float32)
        s = jnp.einsum("bkrcd,bksd->bkrcs", qf, kblk) * scale
        ks = j * bs + jnp.arange(bs)                   # absolute keys
        keep = ks[None, None, :] <= qpos[:, :, None]   # [B, C, BS]
        if window is not None:
            keep &= ks[None, None, :] > qpos[:, :, None] - window
        # keys no query of the lane attends contribute with probability
        # exactly 0 — but 0 * nan == nan, so zero those V rows outright
        # (scratch-block poison must not leak; an attended non-finite
        # still propagates, keeping the engines' isfinite sentinel live)
        vblk = jnp.where(jnp.any(keep, axis=1)[:, None, :, None],
                         vblk, 0.0)
        keep = keep[:, None, None, :, :]               # [B,1,1,C,BS]
        s = jnp.where(keep, s, neg_inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # all-masked-so-far rows carry m == -inf; shifting by 0 keeps
        # exp(-inf) == 0 without manufacturing inf - inf NaNs
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - shift[..., None])
        alpha = jnp.exp(m - shift)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = alpha[..., None] * acc + \
            jnp.einsum("bkrcs,bksd->bkrcd", p, vblk)
        return m_new, l_new, acc_new

    m0 = jnp.full((b, hkv, rep, c), neg_inf)
    l0 = jnp.zeros((b, hkv, rep, c), jnp.float32)
    acc0 = jnp.zeros((b, hkv, rep, c, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nblk, body, (m0, l0, acc0))
    # guard on == 0, not > 0: a nan denominator (genuine attended
    # fault) must divide through and propagate, not silently zero
    out = jnp.where(l[..., None] == 0, 0.0, acc / l[..., None])
    return out.reshape(b, h, c, d).astype(pv.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel: grid (lanes, kv-heads, blocks), table gather in the
# BlockSpec index_map over the scalar-prefetch block table
# ---------------------------------------------------------------------------

def _paged_attn_kernel(tables_ref, qpos_ref, q_ref, k_ref, v_ref, o_ref,
                       m_ref, l_ref, acc_ref, *, scale, window, bs, rep,
                       c):
    """One (lane b, kv-head h, block j) grid step. The pipeline already
    gathered this lane's j-th pool block via the index_map — the kernel
    only scores, masks and folds into the VMEM accumulators, which
    persist across the sequential block dimension."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    j = pl.program_id(2)
    nblk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    qf = q_ref[0, 0].astype(jnp.float32)               # [rep*C, D]
    kb = k_ref[0, 0].astype(jnp.float32)               # [BS, D]
    vb = v_ref[0, 0].astype(jnp.float32)
    s = jnp.dot(qf, kb.T, preferred_element_type=jnp.float32) * scale
    ks = j * bs + jnp.arange(bs)                       # absolute keys
    # row i of the [rep*C, D] query tile is (group r, query c) with c
    # minor — its absolute position is qpos[b, i % C]
    rowpos = jnp.tile(qpos_ref[b], rep)                # [rep*C]
    keep = ks[None, :] <= rowpos[:, None]
    if window is not None:
        keep &= ks[None, :] > rowpos[:, None] - window
    s = jnp.where(keep, s, -jnp.inf)
    # fully-unattended keys get probability 0 but 0 * nan == nan: zero
    # the V rows no query row keeps so scratch poison cannot leak
    vb = jnp.where(jnp.any(keep, axis=0)[:, None], vb, 0.0)
    m_prev = m_ref[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - shift[:, None])
    alpha = jnp.exp(m_prev - shift)
    l_ref[:, 0] = alpha * l_ref[:, 0] + jnp.sum(p, axis=-1)
    acc_ref[...] = alpha[:, None] * acc_ref[...] + \
        jnp.dot(p, vb, preferred_element_type=jnp.float32)
    m_ref[:, 0] = m_new

    @pl.when(j == nblk - 1)
    def _finish():
        # == 0 guard (not > 0): nan denominators must propagate
        l = l_ref[:, 0][:, None]
        o_ref[0, 0] = jnp.where(l == 0, 0.0, acc_ref[...] / l)


@functools.lru_cache(maxsize=None)
def _pallas_call(b, h, c, d, hkv, bs, nblk, scale, window, dtype_name,
                 interpret):
    """Build (and cache) the pallas_call for one static shape family.
    The block table and per-row query positions ride as scalar-prefetch
    operands so the K/V BlockSpec index_maps can address the pool by
    table VALUE — the gather happens in the pipeline, block by block,
    never as a materialised [B, Hkv, nblk*BS, D] array."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rep = h // hkv
    rc = rep * c
    kernel = functools.partial(_paged_attn_kernel, scale=scale,
                               window=window, bs=bs, rep=rep, c=c)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, nblk),
        in_specs=[
            pl.BlockSpec((1, 1, rc, d),
                         lambda bb, hh, jj, tab, qp: (bb, hh, 0, 0)),
            pl.BlockSpec((1, 1, bs, d),
                         lambda bb, hh, jj, tab, qp: (tab[bb, jj], hh,
                                                      0, 0)),
            pl.BlockSpec((1, 1, bs, d),
                         lambda bb, hh, jj, tab, qp: (tab[bb, jj], hh,
                                                      0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, rc, d),
                               lambda bb, hh, jj, tab, qp: (bb, hh, 0,
                                                            0)),
        scratch_shapes=[
            pltpu.VMEM((rc, 1), jnp.float32),          # running max m
            pltpu.VMEM((rc, 1), jnp.float32),          # running denom l
            pltpu.VMEM((rc, d), jnp.float32),          # weighted V acc
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rc, d), jnp.float32),
        interpret=interpret)


def _pallas_core(q, pk, pv, tables, start, scale, window=None):
    """Pallas path: same recurrence as _lax_core, with the block gather
    folded into the kernel pipeline. interpret=True on CPU so tier-1
    parity tests execute the genuine kernel body."""
    import jax
    import jax.numpy as jnp

    b, h, c, d = q.shape
    hkv, bs = pk.shape[1], pk.shape[2]
    nblk = tables.shape[1]
    rep = h // hkv
    qpos = _query_positions(start, b, c)
    # [B, H, C, D] -> [B, Hkv, rep*C, D]: group-major, query-minor rows
    qr = q.astype(jnp.float32).reshape(b, hkv, rep * c, d)
    call = _pallas_call(b, h, c, d, hkv, bs, nblk, float(scale),
                        None if window is None else int(window),
                        str(pk.dtype),
                        jax.default_backend() != "tpu")
    out = call(tables.astype(jnp.int32), qpos, qr, pk, pv)
    return out.reshape(b, h, c, d).astype(pv.dtype)
