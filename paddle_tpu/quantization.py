"""Quantization: QAT fake-quant + PTQ calibration (slim analog).

TPU-native take on the reference slim quantization
(ref python/paddle/fluid/contrib/slim/quantization/quantization_pass.py
QuantizationTransformPass — inserts fake_quantize/dequantize ops into the
program; imperative qat ImperativeQuantAware): instead of a graph pass, QAT
wraps Linear/Conv layers so weights (and optionally activations) pass
through a straight-through-estimator fake-quant — the rewrite the reference
does on ProgramDesc happens here at the Layer level, and XLA fuses the
quant/dequant pair into the matmul. int8 deploy on TPU means bf16/int8
matmuls via XLA; the exported StableHLO carries the q/dq ops.
"""
import numpy as np
import jax
import jax.numpy as jnp

from .framework.tensor import Tensor
from .ops.dispatch import def_op
from . import nn


@def_op("fake_quantize_dequantize", n_tensor_args=1)
def fake_quantize_dequantize(x, bits=8, symmetric=True, scale=None):
    """Straight-through fake quant (ref fake_quantize_op.cc
    FakeQuantizeDequantizeAbsMax): quantize to `bits` then dequantize;
    gradient passes through unchanged. `scale=None` uses the dynamic
    range (QAT); a float scale is the PTQ-calibrated fixed abs-max
    (ref FakeQuantizeDequantizeMovingAverageAbsMax's frozen scale).
    symmetric=False quantizes to the [min, max] range with a zero point.
    All arithmetic stays in x.dtype (a frozen scale must not promote a
    bf16 AMP program to f32)."""
    if symmetric:
        qmax = 2.0 ** (bits - 1) - 1
        s = (jnp.maximum(jnp.max(jnp.abs(x)), 1e-8) / qmax if scale is None
             else jnp.asarray(scale, x.dtype) / qmax)

        def qdq(v):
            return (jnp.clip(jnp.round(v / s), -qmax, qmax) * s) \
                .astype(x.dtype)
    else:
        # asymmetric: affine map of [lo, hi] onto [0, 2^bits - 1]
        qmax = 2.0 ** bits - 1
        if scale is None:
            lo = jnp.min(x)
            hi = jnp.max(x)
        else:
            lo = jnp.asarray(0.0, x.dtype)
            hi = jnp.asarray(scale, x.dtype)
        s = jnp.maximum(hi - lo, 1e-8) / qmax
        zp = jnp.round(-lo / s)

        def qdq(v):
            q = jnp.clip(jnp.round(v / s) + zp, 0, qmax)
            return ((q - zp) * s).astype(x.dtype)

    # straight-through estimator: forward quantized, backward identity
    return x + jax.lax.stop_gradient(qdq(x) - x)


class FakeQuantWrapper(nn.Layer):
    """Wraps one layer; fake-quants its weight (and input activations when
    activation_quantize=True) before the wrapped forward. act_scale=None
    is the dynamic QAT range; a float is a PTQ-calibrated FROZEN range."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 activation_quantize=True, act_scale=None):
        super().__init__()
        self.wrapped = layer
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize = activation_quantize
        self.act_scale = act_scale

    def forward(self, x, *args, **kwargs):
        if self.activation_quantize:
            x = fake_quantize_dequantize(x, bits=self.activation_bits,
                                         scale=self.act_scale)
        w = self.wrapped.weight
        saved = w._data
        w._data = fake_quantize_dequantize(
            Tensor(saved), bits=self.weight_bits)._data
        try:
            out = self.wrapped(x, *args, **kwargs)
        finally:
            w._data = saved
        return out


_QUANTIZABLE = (nn.Linear, nn.Conv2D, nn.Conv1D, nn.Conv3D)


class ImperativeQuantAware:
    """ref slim ImperativeQuantAware: quantize(model) swaps quantizable
    sublayers for fake-quant wrappers in place."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 quantizable_layer_type=None):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.types = tuple(quantizable_layer_type or _QUANTIZABLE)

    def quantize(self, model):
        for holder in model.sublayers(include_self=True) \
                if hasattr(model, "sublayers") else [model]:
            subs = getattr(holder, "_sub_layers", {})
            for name, sub in list(subs.items()):
                if isinstance(sub, self.types):
                    subs[name] = FakeQuantWrapper(
                        sub, self.weight_bits, self.activation_bits)
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        from .static import export
        return export.save(model, path, input_spec=input_spec)


class ScaleObserver:
    """Per-tensor activation-range observer (ref slim
    post_training_quantization.py:121 PostTrainingQuantization's
    sampling): abs_max / avg need one pass; hist / KL need the abs-max
    pass FIRST (fixes the histogram domain), then a histogram pass."""

    BINS = 2048

    def __init__(self, algo="abs_max", bits=8):
        if algo not in ("abs_max", "avg", "hist", "KL"):
            raise ValueError(
                f"unknown PTQ algo {algo!r} (abs_max | avg | hist | KL)")
        self.algo = algo
        self.bits = bits
        self.abs_max = 0.0
        self._batch_maxes = []
        self.hist = np.zeros(self.BINS, "f8") if algo in ("hist", "KL") \
            else None

    def update_max(self, x):
        m = float(jnp.max(jnp.abs(x)))
        self.abs_max = max(self.abs_max, m)
        self._batch_maxes.append(m)

    def update_hist(self, x):
        if self.hist is None or self.abs_max <= 0:
            return
        a = np.abs(np.asarray(x)).ravel()
        h, _ = np.histogram(a, bins=self.BINS, range=(0.0, self.abs_max))
        self.hist += h

    def scale(self):
        """The frozen activation range for this tensor."""
        if self.abs_max <= 0:
            return 0.0
        if self.algo == "abs_max":
            return self.abs_max
        if self.algo == "avg":                   # ref 'avg': mean of
            return float(np.mean(self._batch_maxes))  # per-batch maxes
        if self.algo == "hist":                  # ref hist_percent
            c = np.cumsum(self.hist)
            if c[-1] <= 0:
                return self.abs_max
            idx = int(np.searchsorted(c, 0.99999 * c[-1]))
            return self.abs_max * (idx + 1) / self.BINS
        return self._kl_scale()                  # "KL"

    def _kl_scale(self):
        """TensorRT-style KL threshold search (ref slim cal_kl_threshold):
        pick the clip point whose 2^(bits-1)-level quantization of the
        clipped distribution minimizes KL divergence."""
        target = 2 ** (self.bits - 1)            # 128 for int8
        h = self.hist
        if h.sum() <= 0:
            return self.abs_max
        # search only thresholds that keep >= 99% of the mass: at
        # t == target the `target`-level quantization is EXACT (KL = 0),
        # so an unconstrained argmin always picks maximal clipping — the
        # search's job is to trim the outlier TAIL, not the distribution
        c = np.cumsum(h)
        t99 = int(np.searchsorted(c, 0.99 * c[-1])) + 1
        start = max(target, t99)
        best_t, best_kl = self.BINS, np.inf
        for t in range(start, self.BINS + 1, 16):
            p = h[:t].astype("f8").copy()
            p[-1] += h[t:].sum()                 # clip outliers into edge
            if p.sum() <= 0:
                continue
            # quantize the t bins down to `target` levels and expand back
            chunk = t / target
            q = np.zeros(t, "f8")
            for k in range(target):
                lo, hi = int(np.floor(k * chunk)), int(np.ceil((k + 1)
                                                              * chunk))
                hi = min(hi, t)
                seg = p[lo:hi]
                nz = seg > 0
                if nz.any():
                    # spread the segment's mass over its nonzero bins
                    q[lo:hi][nz] = seg.sum() / int(nz.sum())
            pn = p / p.sum()
            qs = q.sum()
            if qs <= 0:
                continue
            qn = q / qs
            mask = pn > 0
            kl = float(np.sum(pn[mask] * np.log(
                pn[mask] / np.maximum(qn[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_t = kl, t
        return self.abs_max * best_t / self.BINS


class PostTrainingQuantization:
    """PTQ calibration (ref slim post_training_quantization.py:121): run
    representative batches through the model, observe per-layer input
    activation ranges (abs_max / avg / hist / KL), then convert() wraps
    the quantizable sublayers with the FROZEN scales + fake-quant
    weights — the deploy-path half of slim (QAT is the training half)."""

    def __init__(self, model, algo="hist", weight_bits=8,
                 activation_bits=8):
        self.model = model
        self.algo = algo
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.observers = {}
        self.scales = {}

    def _run(self, data_iter, max_batches, update):
        hooks = []

        def mk_hook(name):
            def hook(layer, inputs, outputs=None):
                x = inputs[0]
                update(name, x._data if isinstance(x, Tensor) else x)
            return hook

        for name, sub in self.model.named_sublayers():
            if isinstance(sub, _QUANTIZABLE):
                self.observers.setdefault(
                    name, ScaleObserver(self.algo, self.activation_bits))
                hooks.append(sub.register_forward_pre_hook(mk_hook(name)))
        try:
            for i, batch in enumerate(data_iter):
                if i >= max_batches:
                    break
                x = batch[0] if isinstance(batch, (tuple, list)) else batch
                self.model(x if isinstance(x, Tensor) else Tensor(x))
        finally:
            for h in hooks:
                h.remove()

    def calibrate(self, data, max_batches=16):
        """`data`: any iterable of batches (only the first max_batches
        are drawn — an endless/streaming loader is fine); histogram
        algos replay the drawn batches twice (pass 1 fixes the ranges,
        pass 2 bins)."""
        import itertools
        if not isinstance(data, (list, tuple)):
            data = list(itertools.islice(iter(data), max_batches))
        self._run(iter(data), max_batches,
                  lambda n, x: self.observers[n].update_max(x))
        if self.algo in ("hist", "KL"):
            self._run(iter(data), max_batches,
                      lambda n, x: self.observers[n].update_hist(x))
        self.scales = {n: ob.scale() for n, ob in self.observers.items()}
        return self.scales

    def convert(self):
        """Swap quantizable sublayers for wrappers with the calibrated
        frozen activation scales (ref slim's save_quantized_model
        output: q/dq at fixed ranges)."""
        if not self.scales:
            raise RuntimeError("call calibrate() before convert()")
        for prefix, holder in self.model.named_sublayers(
                include_self=True):
            subs = getattr(holder, "_sub_layers", {})
            for name, sub in list(subs.items()):
                full = f"{prefix}.{name}" if prefix else name
                if isinstance(sub, _QUANTIZABLE) and full in self.scales \
                        and self.scales[full] > 0:
                    subs[name] = FakeQuantWrapper(
                        sub, self.weight_bits, self.activation_bits,
                        act_scale=float(self.scales[full]))
        return self.model


# ---------------------------------------------------------------------------
# inference-side conversion (ref slim/quantization quant2_int8 convert pass:
# the trained/calibrated model's weights become int8 + scales; activations
# dequantize on the fly)
# ---------------------------------------------------------------------------

class QuantizedLinear(nn.Layer):
    """Weight-only int8 Linear: stores the weight as int8 with per-output-
    channel symmetric scales and dequantizes into the matmul dtype at use.
    On TPU this halves weight memory/HBM traffic; the matmul itself runs in
    the activation dtype (XLA fuses the dequant multiply into the matmul's
    operand)."""

    def __init__(self, linear, weight_bits=8):
        super().__init__()
        w = linear.weight._data                      # [in, out]
        qmax = 2 ** (weight_bits - 1) - 1
        scale = jnp.max(jnp.abs(w), axis=0) / qmax   # per out-channel
        scale = jnp.where(scale == 0, 1.0, scale)
        self.register_buffer("w_int8", Tensor(
            jnp.clip(jnp.round(w / scale), -qmax, qmax).astype(jnp.int8)))
        self.register_buffer("w_scale", Tensor(scale))
        self.bias = getattr(linear, "bias", None)
        self.weight_bits = weight_bits
        self._dtype_ref = w.dtype

    def forward(self, x):
        a = x._data if isinstance(x, Tensor) else x
        w = (self.w_int8._data.astype(a.dtype)
             * self.w_scale._data.astype(a.dtype))
        out = jnp.matmul(a, w)
        if self.bias is not None:
            out = out + self.bias._data.astype(a.dtype)
        return Tensor(out)


def convert_to_int8(model, weight_bits=8, quantizable=None):
    """Replace every quantizable sublayer's weights with int8 + scales
    (in place on the Layer tree). Returns the model and the count of
    converted layers (ref save_quantized_model's convert step)."""
    quantizable = quantizable or (nn.Linear,)
    converted = 0

    def visit(layer):
        nonlocal converted
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, quantizable):
                layer._sub_layers[name] = QuantizedLinear(sub, weight_bits)
                converted += 1
            else:
                visit(sub)

    visit(model)
    return model, converted
