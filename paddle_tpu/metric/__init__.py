"""paddle_tpu.metric (ref python/paddle/metric/metrics.py: Metric base,
Accuracy, Precision, Recall, Auc)."""
import numpy as np

from ..framework.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim:  # one-hot or [N,1]
            if label_np.shape[-1] == pred_np.shape[-1]:
                label_np = label_np.argmax(-1)
            else:
                label_np = label_np.reshape(label_np.shape[0])
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = (topk_idx == label_np[..., None])
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        accs = []
        num = c.shape[0]
        for i, k in enumerate(self.topk):
            hit = c[..., :k].sum()
            self.total[i] += hit
            self.count[i] += num
            accs.append(hit / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Histogram AUC (ref metrics.py Auc — same bucketed estimator)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _np(labels).reshape(-1).astype(np.int64)
        idx = np.clip((p * self._num_thresholds).astype(np.int64), 0,
                      self._num_thresholds)
        np.add.at(self._stat_pos, idx[l == 1], 1)
        np.add.at(self._stat_neg, idx[l == 0], 1)

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self._num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos[::-1].cumsum()
        tot_neg = self._stat_neg[::-1].cumsum()
        tp, fp = 0.0, 0.0
        auc = 0.0
        prev_tp, prev_fp = 0.0, 0.0
        for i in range(len(tot_pos)):
            tp, fp = tot_pos[i], tot_neg[i]
            auc += (fp - prev_fp) * (tp + prev_tp) / 2.0
            prev_tp, prev_fp = tp, fp
        if tp == 0 or fp == 0:
            return 0.0
        return float(auc / (tp * fp))

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    from ..ops.math import accuracy as _acc
    return _acc(input, label, k=k)
