"""paddle.inference — the deployment surface (ref paddle/fluid/inference
AnalysisPredictor + api/paddle_inference_api.h; the TRT/Lite/capi engines
are out of scope per SURVEY §7 — XLA is the engine).

TPU-native slice: a predictor over the StableHLO export format
(static/export.py jit.save artifacts). Config/create_predictor keep the
reference call contract:

    config = Config(model_dir)          # a paddle.jit.save'd dir/prefix
    predictor = create_predictor(config)
    out = predictor.run([np_input, ...])
"""
import numpy as np


class Config:
    """ref paddle_infer.Config: carries the model path + knobs. GPU/TRT
    switches are accepted and recorded (XLA owns device placement)."""

    def __init__(self, model_dir=None, params_file=None):
        self.model_dir = model_dir
        self.params_file = params_file
        self._use_gpu = False
        self._device_id = 0
        self._enable_mkldnn = False
        self._cpu_math_threads = 1
        self._memory_optim = True
        self._ir_optim = True

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_gpu = True
        self._device_id = device_id

    def disable_gpu(self):
        self._use_gpu = False

    def enable_mkldnn(self):
        self._enable_mkldnn = True

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = n

    def enable_memory_optim(self):
        self._memory_optim = True

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def model_path(self):
        return self.model_dir


class Predictor:
    """ref AnalysisPredictor: named input/output handles + run(). The
    compiled executable comes from the StableHLO artifact; repeated run()
    calls reuse XLA's compile cache."""

    def __init__(self, config):
        from ..static.export import load
        self._layer = load(config.model_path())
        self._inputs = None

    def get_input_names(self):
        spec = getattr(self._layer, "_input_spec", None)
        if spec:
            return [getattr(s, "name", f"x{i}") or f"x{i}"
                    for i, s in enumerate(spec)]
        return ["x0"]

    def get_output_names(self):
        return ["out0"]

    def run(self, inputs):
        """inputs: list of numpy arrays in input order. Returns a list of
        numpy outputs (ref predictor.run contract)."""
        from ..framework.tensor import Tensor
        outs = self._layer(*[np.asarray(a) for a in inputs])
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        return [np.asarray(o.numpy() if isinstance(o, Tensor) else o)
                for o in outs]


def create_predictor(config):
    return Predictor(config)
