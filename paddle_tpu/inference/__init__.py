"""paddle.inference — the deployment surface (ref paddle/fluid/inference
AnalysisPredictor + api/paddle_inference_api.h + api/analysis_config.cc;
the TRT/Lite/capi engines are out of scope per SURVEY §7 — XLA is the
engine).

Two artifact families serve through one Predictor:
  * StableHLO bundles from paddle.jit.save (static/export.py)
  * reference-saved protobuf models (dirname/__model__ or protobuf
    .pdmodel + LoDTensor params) via static/paddle_compat.py

Config knobs are HONEST: each either takes real effect (memory_optim ->
input-buffer donation in the compiled call; ir_optim=False -> the
uncompiled per-call execution path; cpu_math_threads -> XLA:CPU thread
cap when set before backend init) or warns loudly that XLA owns the
concern (GPU/mkldnn/TensorRT switches).
"""
import os
import warnings

import numpy as np


def _inert(knob, why):
    warnings.warn(
        f"paddle.inference.Config.{knob} has no effect on the TPU build: "
        f"{why}", stacklevel=3)


class Config:
    """ref paddle_infer.Config (api/analysis_config.cc)."""

    def __init__(self, model_dir=None, params_file=None):
        self.model_dir = model_dir
        self.params_file = params_file
        self._memory_optim = True
        self._ir_optim = True
        self._cpu_math_threads = None
        self._llm_opts = None
        self._fleet_opts = None
        self._metrics_exporter = None

    # ---- LLM serving engine (paddle_tpu.serving front door)
    def enable_llm_engine(self, num_slots=4, max_len=256, prefill_len=None,
                          eos_token_id=None, max_queue=None, paged=False,
                          block_size=16, num_blocks=None,
                          speculative=False, draft_config=None, k=4,
                          paged_kernel=None):
        """Arm this Config for create_llm_predictor: slot-count / cache
        horizon / prompt bucket for the continuous-batching engine
        (docs/serving.md). switch_ir_optim(False) carries over as the
        engine's uncompiled per-call path, the same meaning it has for
        the classic Predictor. paged=True serves from the block-table
        paged KV cache (docs/serving.md "Paged KV cache"): HBM scales
        with num_blocks (default: dense-equivalent capacity), prompts
        chunk through `prefill_len`-sized prefill chunks, and identical
        prompt prefixes share blocks. speculative=True (implies paged)
        adds draft-k/verify-once speculative decoding (docs/serving.md
        "Speculative decoding"): a small draft model proposes `k`
        tokens per slot per wave and the target verifies them in ONE
        batched forward, output distribution-identical (bitwise under
        greedy). The draft comes from create_llm_predictor's
        `draft_model=` (pass a model with TRAINED weights loaded — the
        engine snapshots its params at construction) or is built from
        `draft_config` (a config of the target model's family, same
        vocab) — note a draft_config-built draft is freshly
        initialized: correctness holds regardless (the verify step
        guarantees the target distribution), but acceptance — the whole
        speedup — needs a draft that actually predicts the target.
        paged_kernel selects the fused paged-attention implementation
        the engine compiles with ("reference" | "lax" | "pallas" |
        "auto"; default None defers to the PT_PAGED_KERNEL env var,
        then backend auto-selection — nn/paged_attention.py). The
        engine's /healthz reports the resolved kernel."""
        self._llm_opts = {
            "num_slots": int(num_slots),
            "max_len": int(max_len),
            "prefill_len": None if prefill_len is None else int(prefill_len),
            "eos_token_id": eos_token_id,
            "max_queue": max_queue,
            "paged": bool(paged) or bool(speculative),
            "block_size": int(block_size),
            "num_blocks": None if num_blocks is None else int(num_blocks),
            "speculative": bool(speculative),
            "draft_config": draft_config,
            "spec_k": int(k),
            "paged_kernel": paged_kernel,
        }
        return self

    def llm_engine_enabled(self):
        return self._llm_opts is not None

    def enable_llm_fleet(self, replicas=None, policy="affinity",
                         prefill_replicas=None, decode_replicas=None,
                         tenants=None):
        """Serve through a replica fleet instead of one scheduler
        (docs/serving.md "Serving fleet"): create_llm_predictor builds
        `replicas` engines from the enable_llm_engine knobs behind a
        FleetRouter (prefix-affinity routing, token-exact failover,
        elastic scale). Setting prefill_replicas/decode_replicas
        switches to the DISAGGREGATED topology (docs/serving.md
        "Disaggregated prefill/decode"): that many role-pinned prefill
        and decode replicas — a pure split fleet unless `replicas`
        explicitly asks for unified ones alongside (the default is 0
        unified in the split topology, 2 otherwise) — long prompts
        prefill on the prefill side and hand their KV blocks to a
        decode replica.
        `tenants` (an iterable of serving.Tenant, or a prebuilt
        QoSManager) arms multi-tenant QoS — per-tenant SLO windows,
        weighted-fair admission under pool pressure, priority
        preemption (docs/serving.md "Multi-tenant QoS"); submit() then
        accepts tenant=/priority=."""
        disagg = prefill_replicas is not None or decode_replicas is not None
        if replicas is None:
            replicas = 0 if disagg else 2
        self._fleet_opts = {
            "replicas": int(replicas),
            "policy": str(policy),
            "prefill_replicas": (None if prefill_replicas is None
                                 else int(prefill_replicas)),
            "decode_replicas": (None if decode_replicas is None
                                else int(decode_replicas)),
            "tenants": tenants,
        }
        return self

    def llm_fleet_enabled(self):
        return self._fleet_opts is not None

    def enable_metrics_exporter(self, port=0, host="127.0.0.1"):
        """Arm the unified-telemetry /metrics exporter
        (docs/observability.md): create_llm_predictor starts a
        background stdlib-http.server thread serving /metrics
        (Prometheus), /metrics.json and /healthz. port=0 picks a free
        port — read it from predictor.metrics_server.port."""
        self._metrics_exporter = {"port": int(port), "host": str(host)}
        return self

    def metrics_exporter_enabled(self):
        return self._metrics_exporter is not None

    # ---- knobs with real effect
    def enable_memory_optim(self, flag=True):
        """memory_optim (ref analysis_config.cc EnableMemoryOptim):
        donate input buffers to the compiled call so XLA reuses them for
        activations/outputs."""
        self._memory_optim = bool(flag)

    def disable_memory_optim(self):
        self._memory_optim = False

    def switch_ir_optim(self, flag=True):
        """ir_optim=False (ref analysis_config.cc SwitchIrOptim) runs the
        UNOPTIMIZED path: per-call StableHLO replay with no cached
        compiled executable — the analog of serving without the IR pass
        pipeline."""
        self._ir_optim = bool(flag)

    def set_cpu_math_library_num_threads(self, n):
        """Takes effect only before the first backend use (XLA:CPU reads
        the flag at client init) — same constraint the reference has on
        thread-pool construction."""
        self._cpu_math_threads = int(n)
        import jax
        try:
            backend_up = jax._src.xla_bridge._backends  # noqa: SLF001
        except AttributeError:
            backend_up = {}
        if backend_up:
            _inert("set_cpu_math_library_num_threads",
                   "the XLA:CPU client is already initialized; set it "
                   "before the first jax computation")
        else:
            flags = os.environ.get("XLA_FLAGS", "")
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_cpu_multi_thread_eigen="
                f"{'true' if n > 1 else 'false'} "
                f"intra_op_parallelism_threads={n}").strip()

    def set_model(self, model_dir, params_file=None):
        self.model_dir = model_dir
        if params_file is not None:
            self.params_file = params_file

    # ---- knobs XLA owns: accepted for API compat, loudly inert
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        _inert("enable_use_gpu", "device placement is XLA's (the model "
               "runs on the available TPU/CPU backend)")

    def disable_gpu(self):
        pass                      # already not-GPU; nothing to disable

    def enable_mkldnn(self):
        _inert("enable_mkldnn", "XLA:CPU replaces the mkldnn kernels")

    def enable_tensorrt_engine(self, *args, **kwargs):
        _inert("enable_tensorrt_engine", "XLA is the execution engine; "
               "there is no TensorRT subgraph pass")

    def enable_lite_engine(self, *args, **kwargs):
        _inert("enable_lite_engine", "XLA is the execution engine")

    def model_path(self):
        return self.model_dir

    def memory_optim_enabled(self):
        return self._memory_optim

    def ir_optim(self):
        return self._ir_optim


class Predictor:
    """ref AnalysisPredictor: named input/output handles + run().

    StableHLO artifacts execute through ONE jitted call (params/buffers
    captured, inputs donated when memory_optim); reference protobuf
    models execute through the standard Executor."""

    def __init__(self, config):
        self._config = config
        path = config.model_path()
        self._mode = None
        self._pending = {}         # handle-fed inputs (ZeroCopyRun style)
        self._last_outputs = None
        if not path:
            raise ValueError(
                "inference Config has no model path — construct it as "
                "Config(model_dir) or call config.set_model(path)")
        if os.path.exists(path + ".meta.json"):
            self._init_stablehlo(path, config)
        else:
            self._init_program(path, config)

    # ---- StableHLO bundle (paddle.jit.save)
    def _init_stablehlo(self, path, config):
        import jax
        from ..static.export import load
        self._mode = "stablehlo"
        self._layer = load(path)
        ex = self._layer._exported

        def call(params, buffers, *xs):
            return ex.call(params, buffers, *xs)

        if config.ir_optim():
            # donate the per-call input buffers; params/buffers persist
            n_fixed = 2
            spec = self._layer._meta.get("inputs", [])
            donate = tuple(range(n_fixed, n_fixed + len(spec))) \
                if config.memory_optim_enabled() else ()
            self._run = jax.jit(call, donate_argnums=donate)
        else:
            self._run = call            # uncompiled per-call replay

    # ---- reference protobuf / native JSON program
    def _init_program(self, path, config):
        from ..static import load_inference_model, Executor
        self._mode = "program"
        prog, feeds, fetches = load_inference_model(
            path, params_filename=config.params_file)
        self._prog, self._feeds, self._fetches = prog, feeds, fetches
        self._exe = Executor()
        if not config.ir_optim():
            _inert("switch_ir_optim(False)",
                   "program-path serving always executes the jit-compiled "
                   "program (there is no unoptimized interpreter for it)")

    def get_input_names(self):
        if self._mode == "program":
            return list(self._feeds)
        spec = self._layer._meta.get("inputs", [])
        return [s.get("name") or f"x{i}" if isinstance(s, dict) else f"x{i}"
                for i, s in enumerate(spec)] or ["x0"]

    def get_output_names(self):
        if self._mode == "program":
            return list(self._fetches)
        return [f"out{i}"
                for i in range(self._layer._meta.get("n_outputs", 1))]

    def get_input_handle(self, name):
        """ref paddle_infer.Predictor.get_input_handle — the zero-copy
        serving surface: handle.reshape/copy_from_cpu, run(),
        output handle.copy_to_cpu()."""
        if name not in self.get_input_names():
            raise KeyError(f"no input named {name!r}; "
                           f"inputs: {self.get_input_names()}")
        return _TensorHandle(self, name, is_input=True)

    def get_output_handle(self, name):
        if name not in self.get_output_names():
            raise KeyError(f"no output named {name!r}; "
                           f"outputs: {self.get_output_names()}")
        return _TensorHandle(self, name, is_input=False)

    def run(self, inputs=None):
        """inputs: list of numpy arrays in input order — or None for the
        handle style (ref ZeroCopyRun: feed via get_input_handle, read
        via get_output_handle). Returns a list of numpy outputs."""
        import jax.numpy as jnp
        from ..framework.tensor import Tensor
        if inputs is None:
            names = self.get_input_names()
            missing = [n for n in names if n not in self._pending]
            if missing:
                raise RuntimeError(
                    "inputs not fed via get_input_handle()."
                    f"copy_from_cpu(): {missing}")
            outs = self.run([self._pending[n] for n in names])
            self._last_outputs = outs
            return True
        if self._mode == "program":
            outs = self._exe.run(self._prog,
                                 feed=dict(zip(self._feeds, inputs)),
                                 fetch_list=self._fetches)
            outs = [np.asarray(o) for o in outs]
            self._last_outputs = outs     # output handles track EVERY run
            return outs
        donating = (self._config.memory_optim_enabled()
                    and self._config.ir_optim())
        arrays = []
        for a in inputs:
            if isinstance(a, Tensor):
                # donation would invalidate the caller's live Tensor —
                # hand the compiled call its own copy instead
                arrays.append(jnp.copy(a._data) if donating else a._data)
            else:
                arrays.append(jnp.asarray(a))
        outs = self._run(self._layer._params, self._layer._buffers, *arrays)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        outs = [np.asarray(o.numpy() if isinstance(o, Tensor) else o)
                for o in outs]
        self._last_outputs = outs         # output handles track EVERY run
        return outs


class _TensorHandle:
    """ref paddle_api.h ZeroCopyTensor / paddle_infer.Tensor: the
    handle-based serving surface (reshape + copy_from_cpu on inputs,
    copy_to_cpu on outputs)."""

    def __init__(self, predictor, name, is_input):
        self._p = predictor
        self.name = name
        self._is_input = is_input
        self._shape = None

    def reshape(self, shape):
        self._shape = tuple(int(s) for s in shape)

    def copy_from_cpu(self, data):
        if not self._is_input:
            raise RuntimeError(f"'{self.name}' is an output handle")
        arr = np.asarray(data)
        if self._shape is not None:
            arr = arr.reshape(self._shape)
        self._p._pending[self.name] = arr

    def copy_to_cpu(self):
        if self._is_input:
            raise RuntimeError(f"'{self.name}' is an input handle")
        outs = self._p._last_outputs
        if outs is None:
            raise RuntimeError("run() has not been called yet")
        return outs[self._p.get_output_names().index(self.name)]

    def shape(self):
        if self._is_input:
            return list(self._shape or ())
        return list(self.copy_to_cpu().shape)


def create_predictor(config):
    return Predictor(config)


class LLMPredictor:
    """Serving-engine analog of Predictor: one Config-built Scheduler +
    ServingEngine pair with a blocking generate() for the simple case and
    the full submit()/run() surface for continuous batching."""

    def __init__(self, config, model, draft_model=None):
        from ..serving import Scheduler
        from ..serving.fleet import DisaggFleetRouter, FleetRouter
        opts = config._llm_opts or {}
        self._eos_token_id = opts.get("eos_token_id")
        factory = _engine_factory(config, opts, model, draft_model)
        self.router = None
        fleet_opts = config._fleet_opts
        if fleet_opts is None:
            self.engine = factory()
            self.scheduler = Scheduler(self.engine,
                                       max_queue=opts.get("max_queue"))
        else:
            # fleet front door: replicas built from the SAME factory the
            # single-engine path uses, so every enable_llm_engine knob
            # (paged, speculative, kernel choice, ir_optim) carries over
            sched_kw = ({} if opts.get("max_queue") is None
                        else {"max_queue": opts["max_queue"]})
            if (fleet_opts["prefill_replicas"] is not None
                    or fleet_opts["decode_replicas"] is not None):
                self.router = DisaggFleetRouter(
                    factory,
                    prefill_replicas=fleet_opts["prefill_replicas"] or 0,
                    decode_replicas=fleet_opts["decode_replicas"] or 0,
                    unified_replicas=fleet_opts["replicas"],
                    qos=fleet_opts["tenants"],
                    policy=fleet_opts["policy"],
                    scheduler_kwargs=sched_kw)
            else:
                self.router = FleetRouter(
                    factory, replicas=fleet_opts["replicas"],
                    policy=fleet_opts["policy"],
                    scheduler_kwargs=sched_kw)
            self.engine = None
            self.scheduler = None
        self.metrics_server = None
        if config.metrics_exporter_enabled():
            target = self.engine if self.router is None else self.router
            self.metrics_server = target.start_metrics_server(
                **config._metrics_exporter)

    def close(self, drain=True):
        """Graceful shutdown: drain the scheduler (accepted requests
        complete, new submits are shed with finish_reason "rejected")
        and stop the background metrics exporter. drain=False skips the
        wave loop for a hard stop. The engine's compiled programs need
        no teardown."""
        if self.router is not None:
            if drain:
                self.router.shutdown()
            else:
                self.router.stop_metrics_server()
        elif drain:
            self.scheduler.shutdown()
        else:
            self.engine.stop_metrics_server()
        self.metrics_server = None

    def generate(self, prompt, **kw):
        kw.setdefault("eos_token_id", self._eos_token_id)
        if self.router is not None:
            return self.router.generate(prompt, **kw)
        return self.scheduler.generate(prompt, **kw)

    def submit(self, **kw):
        kw.setdefault("eos_token_id", self._eos_token_id)
        if self.router is not None:
            return self.router.submit(**kw)
        return self.scheduler.submit(**kw)

    def run(self, **kw):
        if self.router is not None:
            return self.router.run(**kw)
        return self.scheduler.run(**kw)

    def health(self):
        """Engine (or fleet) health payload — what /healthz serves."""
        if self.router is not None:
            return self.router.health()
        return self.engine.health()

    @property
    def metrics(self):
        if self.router is not None:
            return self.router.metrics
        return self.scheduler.metrics


def _engine_factory(config, opts, model, draft_model):
    """One closure building the Config-described engine — called once
    for a single-engine predictor, once per replica for a fleet."""
    from ..serving import (PagedServingEngine, ServingEngine,
                           SpeculativePagedEngine)
    if opts.get("speculative") and draft_model is None:
        draft_cfg = opts.get("draft_config")
        if draft_cfg is None:
            raise ValueError(
                "speculative serving needs a draft model: pass "
                "draft_model= to create_llm_predictor or "
                "draft_config= to enable_llm_engine")
        # same family as the target: the configs carry the family, the
        # model class carries the architecture. Built ONCE here so a
        # fleet's replicas share one draft (digest-identical state).
        draft_model = type(model)(draft_cfg)

    def factory():
        if opts.get("speculative"):
            return SpeculativePagedEngine(
                model, draft_model,
                spec_k=opts.get("spec_k", 4),
                num_slots=opts.get("num_slots", 4),
                max_len=opts.get("max_len", 256),
                block_size=opts.get("block_size", 16),
                num_blocks=opts.get("num_blocks"),
                prefill_chunk_len=opts.get("prefill_len"),
                paged_kernel=opts.get("paged_kernel"),
                jit_compile=config.ir_optim())
        if opts.get("paged"):
            return PagedServingEngine(
                model,
                num_slots=opts.get("num_slots", 4),
                max_len=opts.get("max_len", 256),
                block_size=opts.get("block_size", 16),
                num_blocks=opts.get("num_blocks"),
                prefill_chunk_len=opts.get("prefill_len"),
                paged_kernel=opts.get("paged_kernel"),
                jit_compile=config.ir_optim())
        return ServingEngine(
            model,
            num_slots=opts.get("num_slots", 4),
            max_len=opts.get("max_len", 256),
            prefill_len=opts.get("prefill_len"),
            jit_compile=config.ir_optim())
    return factory


def create_llm_predictor(config, model=None, draft_model=None):
    """Front door from the inference Config to paddle_tpu.serving: the
    Config carries the engine knobs (enable_llm_engine: slots, cache
    horizon, prefill bucket, eos, queue bound, speculative draft;
    switch_ir_optim(False) -> uncompiled engine;
    set_cpu_math_library_num_threads applies as for any predictor) and
    `model` is a causal LM exposing prefill/decode_step/init_cache
    (nlp.LlamaForCausalLM, nlp.GPTForPretraining). `draft_model` (same
    family + vocab, typically far fewer layers) serves the speculative
    configuration. LLM weights load through the model constructors +
    paddle.load — there is no protobuf/StableHLO artifact path for the
    decode-cache entry points."""
    if model is None:
        raise ValueError(
            "create_llm_predictor needs `model` (a causal LM with "
            "prefill/decode_step/init_cache); the classic artifact paths "
            "(create_predictor) have no KV-cache decode entry points")
    if not config.llm_engine_enabled():
        config.enable_llm_engine()
    return LLMPredictor(config, model, draft_model=draft_model)
