"""Weight-decay regularizers (ref python/paddle/fluid/regularizer.py L1Decay /
L2Decay appended to gradients at optimize time)."""
import jax.numpy as jnp


class WeightDecayRegularizer:
    def _append(self, p, g):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def _append(self, p, g):
        return g + self._coeff * p

    def __repr__(self):
        return f"L2Decay({self._coeff})"


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def _append(self, p, g):
        return g + self._coeff * jnp.sign(p)

    def __repr__(self):
        return f"L1Decay({self._coeff})"


L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
