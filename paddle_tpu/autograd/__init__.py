"""paddle.autograd — user-defined differentiable functions + grad API
(ref python/paddle/autograd/py_layer.py PyLayer/PyLayerContext; the
reference's C++ side is imperative/py_layer_fcns — here the tape engine
consumes the Python backward directly as a GradNode vjp).

Also re-exports `backward` and the double-grad `grad` from the tape.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..framework.tape import GradNode
from ..framework import state

__all__ = ["PyLayer", "PyLayerContext", "backward", "grad"]


class PyLayerContext:
    """Passed as ctx to forward/backward (ref py_layer.py PyLayerContext)."""

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved


class PyLayer:
    """Custom autograd op:

        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, grad_out):
                (x,) = ctx.saved_tensor()
                return 3 * x * x * grad_out

        y = Cube.apply(x)

    backward returns one grad per DIFFERENTIABLE tensor input of forward
    (None allowed for non-differentiable ones), like the reference.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with state.no_grad_ctx():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = tuple(out) if multi else (out,)
        for o in outs:
            if not isinstance(o, Tensor):
                raise TypeError(
                    f"{cls.__name__}.forward must return Tensor(s), got "
                    f"{type(o).__name__}")

        for k, v in kwargs.items():
            if isinstance(v, Tensor) and not v.stop_gradient:
                raise TypeError(
                    f"{cls.__name__}.apply: differentiable Tensor passed "
                    f"as keyword {k!r}; tensors must be positional so "
                    "backward grads align with them")
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        needs_grad = state.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        if not needs_grad:
            return out

        def vjp(cots):
            cots = cots if isinstance(cots, tuple) else (cots,)
            with state.no_grad_ctx():
                gs = cls.backward(ctx, *[Tensor(c) for c in cots])
            gs = gs if isinstance(gs, (tuple, list)) else (gs,)
            if len(gs) != len(tensor_inputs):
                raise ValueError(
                    f"{cls.__name__}.backward returned {len(gs)} grads "
                    f"for {len(tensor_inputs)} tensor inputs")
            return tuple(
                None if g is None else
                (g._data if isinstance(g, Tensor) else jnp.asarray(g))
                for g in gs)

        node = GradNode(
            vjp=vjp,
            inputs=[t if not t.stop_gradient else None
                    for t in tensor_inputs],
            n_outputs=len(outs),
            out_shapes=tuple(o.shape for o in outs),
            out_dtypes=tuple(o.dtype for o in outs),
            name=cls.__name__,
        )
        fresh = []
        for i, o in enumerate(outs):
            w = Tensor(o._data, stop_gradient=False)
            w._node = node
            w._slot = i
            fresh.append(w)
        return tuple(fresh) if multi else fresh[0]


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward: reverse sweeps from one or more tensors.
    Shared subgraphs survive across the per-tensor sweeps (every sweep
    but the last retains the graph regardless of `retain_graph`)."""
    from ..framework import tape
    ts = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if isinstance(grad_tensors, (list, tuple)):
        if len(grad_tensors) != len(ts):
            raise ValueError(
                f"backward: {len(ts)} tensors but {len(grad_tensors)} "
                "grad_tensors")
        gs = list(grad_tensors)
    else:
        gs = [grad_tensors] * len(ts)
    for i, (t, g) in enumerate(zip(ts, gs)):
        keep = retain_graph or i < len(ts) - 1
        tape.backward(t, g, retain_graph=keep)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad — delegate to the framework-level implementation."""
    import paddle_tpu as pt
    return pt.grad(outputs, inputs, grad_outputs=grad_outputs,
                   retain_graph=retain_graph, create_graph=create_graph,
                   only_inputs=only_inputs, allow_unused=allow_unused,
                   no_grad_vars=no_grad_vars)
