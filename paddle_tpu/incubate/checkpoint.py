"""Auto-checkpoint: transparent epoch-range snapshot/restore for elastic jobs.

TPU-native analog of the reference auto-checkpoint
(ref python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:71
AutoCheckpointChecker env contract, :189/:265 TrainEpochRange,
checkpoint_saver.py): a relaunched job resumes from the last completed
epoch without the training script changing. HDFS is replaced by a
filesystem directory (point it at a mounted GCS bucket on a pod — the
TPU-world equivalent of the reference's HDFS ugi env).

Usage (same shape as the reference):
    for epoch in train_epoch_range(10, save_dir, model=m, optimizer=o):
        train_one_epoch(...)
On restart with the same job id, completed epochs are skipped and
model/optimizer state is restored from the newest snapshot.
"""
import json
import os
import shutil
import tempfile

from ..framework.serialization import save as _save, load as _load


class AutoCheckpointChecker:
    """Reads the job env (ref auto_checkpoint.py:71): PADDLE_JOB_ID names the
    checkpoint namespace; PADDLE_CKPT_DIR overrides the directory."""

    def __init__(self):
        self.job_id = os.environ.get("PADDLE_JOB_ID", "default_job")
        self.ckpt_dir = os.environ.get("PADDLE_CKPT_DIR")

    @property
    def valid(self):
        return True


def _meta_path(root):
    return os.path.join(root, "range_meta.json")


class TrainEpochRange:
    """ref auto_checkpoint.py:265. Iterates [start, max_epoch_num); snapshots
    model/optimizer/user state after each epoch; resumes after relaunch."""

    def __init__(self, max_epoch_num, save_dir, model=None, optimizer=None,
                 name=None, save_checkpoint_inter=1):
        checker = AutoCheckpointChecker()
        self.name = name or checker.job_id
        self.root = os.path.join(checker.ckpt_dir or save_dir, self.name)
        self.max_epoch_num = max_epoch_num
        self.model = model
        self.optimizer = optimizer
        self.inter = max(1, save_checkpoint_inter)
        self._start = 0
        os.makedirs(self.root, exist_ok=True)
        # sweep .saving_* temp dirs orphaned by a hard kill mid-save
        for d in os.listdir(self.root):
            if d.startswith(".saving_"):
                shutil.rmtree(os.path.join(self.root, d),
                              ignore_errors=True)
        self._restore()

    # ------------------------------------------------------------- persistence
    def _restore(self):
        meta = _meta_path(self.root)
        if not os.path.exists(meta):
            return
        try:
            with open(meta) as f:
                info = json.load(f)
        except (ValueError, OSError):
            return  # torn meta write: start over rather than crash
        epoch = info.get("last_completed_epoch", -1)
        if epoch < 0:
            return
        snap = os.path.join(self.root, f"epoch_{epoch}")
        if self.model is not None:
            sd = _load(os.path.join(snap, "model.pdparams"))
            self.model.set_state_dict(sd)
        if self.optimizer is not None and os.path.exists(
                os.path.join(snap, "opt.pdopt")):
            sd = _load(os.path.join(snap, "opt.pdopt"))
            self.optimizer.set_state_dict(sd)
        self._start = epoch + 1

    def _snapshot(self, epoch):
        # write to a temp dir then atomically rename + update meta, so a
        # kill mid-save never corrupts the newest usable snapshot
        final = os.path.join(self.root, f"epoch_{epoch}")
        tmp = tempfile.mkdtemp(dir=self.root, prefix=".saving_")
        try:
            if self.model is not None:
                _save(dict(self.model.state_dict()),
                      os.path.join(tmp, "model.pdparams"))
            if self.optimizer is not None and hasattr(
                    self.optimizer, "state_dict"):
                _save(self.optimizer.state_dict(),
                      os.path.join(tmp, "opt.pdopt"))
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        with open(_meta_path(self.root) + ".tmp", "w") as f:
            json.dump({"last_completed_epoch": epoch,
                       "max_epoch_num": self.max_epoch_num}, f)
        os.replace(_meta_path(self.root) + ".tmp", _meta_path(self.root))
        # keep only the latest snapshot (ref checkpoint_saver keeps max_num);
        # orphaned .saving_* dirs are swept by the constructor on restart
        for d in os.listdir(self.root):
            if d.startswith("epoch_") and d != f"epoch_{epoch}":
                shutil.rmtree(os.path.join(self.root, d),
                              ignore_errors=True)

    # ------------------------------------------------------------- iteration
    def get(self):
        for epoch in range(self._start, self.max_epoch_num):
            yield epoch
            if (epoch + 1) % self.inter == 0 or \
                    epoch == self.max_epoch_num - 1:
                self._snapshot(epoch)

    def __iter__(self):
        return self.get()

    @property
    def restored_from(self):
        return self._start - 1 if self._start > 0 else None


def train_epoch_range(max_epoch_num, save_dir, model=None, optimizer=None,
                      **kwargs):
    """ref auto_checkpoint.py train_epoch_range entry point."""
    return TrainEpochRange(max_epoch_num, save_dir, model=model,
                           optimizer=optimizer, **kwargs)
