"""Mixture-of-Experts with expert parallelism over the 'ep' mesh axis.

New capability relative to the reference (Yelrose/Paddle ~2.0 has no MoE;
later Paddle grew incubate.distributed.models.moe — this is the TPU-first
take on that surface, GShard/Switch-style).

TPU-native design:
  - Experts live in STACKED parameters w1:[E, D, H], w2:[E, H, D] carrying
    a PartitionSpec('ep', ...) hint — under a mesh with an 'ep' axis the
    GSPMD partitioner shards the expert dim and inserts the all-to-alls
    for dispatch/combine on its own; no hand-written collectives.
  - Dispatch/combine are dense einsums over a one-hot [B*S, E, C]
    dispatch tensor (no gather/scatter, no dynamic shapes): XLA maps them
    onto the MXU and fuses the masking. Capacity C bounds per-expert work
    to a static shape; overflowing tokens fall through the residual
    connection (standard GShard behavior).
  - Top-k gating in f32 with the load-balancing auxiliary loss of
    Shazeer et al. (fraction-of-tokens x mean-gate-prob per expert).
"""
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..framework.tensor import Tensor
from ..nn import initializer as I
from ..distributed import mesh as mesh_mod


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def moe_dispatch(gate_logits, k, capacity):
    """Gating + dispatch plan. gate_logits: [N, E] (N = B*S tokens).

    Returns (dispatch [N, E, C] one-hot-ish f32, combine [N, E, C] f32,
    aux_loss scalar). A token's c-th slot holds its position within the
    expert's capacity buffer; tokens past capacity get zero rows (they
    ride the residual stream)."""
    n, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

    # aux load-balance loss over the TOP-1 assignment (Switch/GShard)
    top1 = jnp.argmax(probs, axis=-1)
    frac_tokens = jnp.mean(_one_hot(top1, e), axis=0)        # [E]
    frac_probs = jnp.mean(probs, axis=0)                     # [E]
    aux = e * jnp.sum(frac_tokens * frac_probs)

    dispatch = jnp.zeros((n, e, capacity), jnp.float32)
    combine = jnp.zeros((n, e, capacity), jnp.float32)
    # route the k choices sequentially so capacity counters accumulate
    remaining = probs
    used = jnp.zeros((e,), jnp.int32)                        # slots taken
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)              # [N]
        gate = jnp.take_along_axis(remaining, choice[:, None],
                                   axis=-1)[:, 0]            # [N]
        remaining = remaining * (1.0 - _one_hot(choice, e))
        sel = _one_hot(choice, e)                            # [N, E]
        # position of each token within its chosen expert's buffer:
        # running count of earlier tokens routed to the same expert
        pos_in_e = (jnp.cumsum(sel, axis=0) - sel) \
            + used[None, :].astype(jnp.float32)              # [N, E]
        pos = jnp.sum(pos_in_e * sel, axis=-1).astype(jnp.int32)  # [N]
        ok = pos < capacity
        slot = _one_hot(jnp.where(ok, pos, capacity), capacity + 1)
        slot = slot[:, :capacity]                            # drop overflow
        d = sel[:, :, None] * slot[:, None, :]               # [N, E, C]
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        used = used + jnp.sum(
            d, axis=(0, 2)).astype(jnp.int32)
    return dispatch, combine, aux


class MoELayer(nn.Layer):
    """MoE FFN. forward(x) -> (y, aux_loss): the weighted load-balance
    loss is RETURNED, not stashed — it must flow through the data path so
    it stays a valid tracer under jit/remat and can't cross-contaminate
    between models (callers add it to their task loss). Overflow tokens
    contribute zero combine rows and ride the caller's residual."""

    def __init__(self, d_model, d_hidden, num_experts, k=2,
                 capacity_factor=1.25, aux_weight=0.01,
                 initializer_range=0.02):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.k = int(k)
        self.capacity_factor = float(capacity_factor)
        self.aux_weight = float(aux_weight)
        init = I.Normal(0.0, initializer_range)
        self.gate = nn.Linear(d_model, num_experts,
                              weight_attr=nn.ParamAttr(initializer=init),
                              bias_attr=False)
        self.w1 = self.create_parameter(
            [num_experts, d_model, d_hidden], default_initializer=init)
        self.b1 = self.create_parameter(
            [num_experts, 1, d_hidden],
            default_initializer=I.Constant(0.0))
        self.w2 = self.create_parameter(
            [num_experts, d_hidden, d_model],
            default_initializer=I.Normal(
                0.0, initializer_range / math.sqrt(2.0)))
        self.b2 = self.create_parameter(
            [num_experts, 1, d_model], default_initializer=I.Constant(0.0))
        # expert-parallel sharding hints: GSPMD shards the expert dim
        self.w1.sharding = P(mesh_mod.EP_AXIS, None, None)
        self.b1.sharding = P(mesh_mod.EP_AXIS, None, None)
        self.w2.sharding = P(mesh_mod.EP_AXIS, None, None)
        self.b2.sharding = P(mesh_mod.EP_AXIS, None, None)

    def forward(self, x):
        from ..ops.dispatch import apply

        def f(x_, w1, b1, w2, b2, gw):
            b, s, d = x_.shape
            nt = b * s
            xt = x_.reshape(nt, d)
            logits = xt.astype(jnp.float32) @ gw.astype(jnp.float32)
            cap = max(1, int(self.capacity_factor * nt * self.k
                             / self.num_experts))
            dispatch, combine, aux = moe_dispatch(logits, self.k, cap)
            # [E, C, D] expert inputs; keep the expert dim sharded on 'ep'
            ein = jnp.einsum("nec,nd->ecd", dispatch.astype(x_.dtype), xt)
            ein = self._constrain(ein)
            h = jax.nn.gelu(
                jnp.einsum("ecd,edh->ech", ein, w1) + b1.astype(x_.dtype))
            eout = jnp.einsum("ech,ehd->ecd", h, w2) + b2.astype(x_.dtype)
            eout = self._constrain(eout)
            y = jnp.einsum("nec,ecd->nd", combine.astype(x_.dtype), eout)
            return y.reshape(b, s, d), aux

        w = self.gate.weight
        y, aux = apply(f, (x, self.w1, self.b1, self.w2, self.b2, w),
                       name="moe_layer")
        return y, aux * self.aux_weight

    def _constrain(self, arr):
        mesh = mesh_mod.get_mesh()
        if mesh is not None and mesh_mod.EP_AXIS in mesh.axis_names \
                and arr.shape[0] % int(mesh.shape[mesh_mod.EP_AXIS]) == 0:
            try:
                return jax.lax.with_sharding_constraint(
                    arr, jax.sharding.NamedSharding(
                        mesh, P(mesh_mod.EP_AXIS, None, None)))
            except (ValueError, RuntimeError):
                return arr
        return arr

