"""Recompute / activation checkpointing (ref fluid/optimizer.py:4549
RecomputeOptimizer + meta_optimizers/recompute_optimizer.py).

TPU-native: jax.checkpoint (remat) on the segment — XLA re-executes the
forward inside the backward, trading FLOPs for HBM exactly like the reference's
recompute pass but without program rewriting. Closed-over parameters are
treated as saved residuals (weights kept, activations recomputed).
Eager mode runs the segment normally (the tape stores residuals; eager
recompute is a memory no-op under PJRT).
"""
import jax

from ..framework import state
from ..framework.tensor import Tensor


def recompute(function, *args, preserve_rng_state=True, **kwargs):
    if not state.is_functional_mode():
        return function(*args, **kwargs)

    tensor_args = [a for a in args if isinstance(a, Tensor)]
    arrays = [t._data for t in tensor_args]

    def pure(*arrs):
        it = iter(arrs)
        rebuilt = [Tensor(next(it)) if isinstance(a, Tensor) else a
                   for a in args]
        out = function(*rebuilt, **kwargs)
        if isinstance(out, Tensor):
            return out._data
        if isinstance(out, (list, tuple)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)
        return out

    out = jax.checkpoint(pure)(*arrays)
    if isinstance(out, tuple):
        return tuple(Tensor(o, stop_gradient=False) for o in out)
    return Tensor(out, stop_gradient=False)


def recompute_sequential(functions, x, segments=1):
    """Checkpoint a Sequential in `segments` chunks (ref recompute segment
    semantics)."""
    import numpy as np
    layers = list(functions)
    n = len(layers)
    seg_size = max(1, n // max(segments, 1))
    i = 0
    while i < n:
        chunk = layers[i:i + seg_size]

        def seg_fn(inp, chunk=chunk):
            for l in chunk:
                inp = l(inp)
            return inp

        x = recompute(seg_fn, x)
        i += seg_size
    return x
