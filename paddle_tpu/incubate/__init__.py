"""paddle_tpu.incubate (ref python/paddle/fluid/incubate): auto-checkpoint etc."""
from . import recompute  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import train_epoch_range, TrainEpochRange  # noqa: F401
# ref python/paddle/incubate: optimizer wrappers surface here too
from ..optimizer.wrappers import (ModelAverage,  # noqa: F401
                                  LookaheadOptimizer as LookAhead)


def _segment(pool_type):
    def fn(data, segment_ids, name=None, num_segments=None):
        """ref python/paddle/incubate/tensor/math.py segment_{sum,mean,
        max,min} over the registered segment_pool op (ops/legacy.py).
        Pass num_segments explicitly under jit tracing (static shapes)."""
        from ..ops.legacy import segment_pool
        return segment_pool(data, segment_ids, pool_type=pool_type,
                            num_segments=num_segments)
    fn.__name__ = f"segment_{pool_type.lower()}"
    return fn


segment_sum = _segment("SUM")
segment_mean = _segment("MEAN")
segment_max = _segment("MAX")
segment_min = _segment("MIN")


def __getattr__(name):
    if name == "moe":
        import importlib
        return importlib.import_module(__name__ + ".moe")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
