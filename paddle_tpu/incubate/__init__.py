"""paddle_tpu.incubate (ref python/paddle/fluid/incubate): auto-checkpoint etc."""
from . import recompute  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import train_epoch_range, TrainEpochRange  # noqa: F401
