"""paddle_tpu.incubate (ref python/paddle/fluid/incubate): auto-checkpoint etc."""
