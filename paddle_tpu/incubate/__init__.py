"""paddle_tpu.incubate (ref python/paddle/fluid/incubate): auto-checkpoint etc."""
from . import recompute  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import train_epoch_range, TrainEpochRange  # noqa: F401
# ref python/paddle/incubate: optimizer wrappers surface here too
from ..optimizer.wrappers import (ModelAverage,  # noqa: F401
                                  LookaheadOptimizer as LookAhead)


def __getattr__(name):
    if name == "moe":
        import importlib
        return importlib.import_module(__name__ + ".moe")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
