"""paddle.tensor namespace (ref python/paddle/tensor): re-exports the op
library by category, mirroring the reference's module layout. Only
functions DEFINED in each ops module are exported (no star-import
leakage of jnp/Tensor/dispatch helpers)."""
from ..ops import math, manipulation, creation, logic, linalg  # noqa: F401


def _reexport(mod):
    out = {}
    for name in dir(mod):
        if name.startswith("_"):
            continue
        obj = getattr(mod, name)
        if callable(obj) and getattr(obj, "__module__", "") == mod.__name__:
            out[name] = obj
    return out


# creation last so shared names (e.g. assign) resolve like the top-level
# package, which imports creation's explicitly
for _mod in (math, manipulation, logic, creation):
    globals().update(_reexport(_mod))
del _mod, _reexport
