"""Dataset zoo (ref python/paddle/vision/datasets: MNIST, Cifar10/100,
FashionMNIST + paddle/dataset loaders).

REAL data by default when present: each dataset probes the standard cache
home (`$PADDLE_TPU_DATA_HOME`, default ~/.cache/paddle_tpu/dataset/...)
for the canonical files (idx-ubyte[.gz] for MNIST-family,
cifar-10-batches-bin for CIFAR) and parses them with format-faithful
readers — the same files the reference's downloader fetches
(ref python/paddle/dataset/mnist.py, cifar.py). This build environment has
zero egress, so when no files exist the loaders fall back to deterministic
synthetic data with learnable class signal (convergence tests stay
meaningful); the format readers themselves are exercised by
tests/test_datasets_real.py against genuine idx/cifar-bin files written
locally."""
import gzip
import os
import struct
import tarfile

import numpy as np

from ..io import Dataset


def data_home():
    return os.path.expanduser(os.environ.get(
        "PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


class _SyntheticImageDataset(Dataset):
    """Deterministic fake data with learnable signal: class-dependent mean
    patterns so convergence tests exercise real learning."""

    def __init__(self, num_samples, image_shape, num_classes, transform=None,
                 seed=0, pattern_seed=1234):
        self.num_samples = num_samples
        self.image_shape = image_shape
        self.num_classes = num_classes
        self.transform = transform
        # class patterns are split-independent (train and test must share the
        # underlying "digit shapes"); `seed` only varies the noise + labels
        self._patterns = np.random.RandomState(pattern_seed).rand(
            num_classes, *image_shape).astype(np.float32)
        rng = np.random.RandomState(seed)
        self._labels = rng.randint(0, num_classes, num_samples)
        self._seed = seed * 1_000_003

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seed + idx + 1)
        label = self._labels[idx]
        img = (self._patterns[label]
               + 0.3 * rng.randn(*self.image_shape).astype(np.float32))
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), np.int64(label)

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """ref python/paddle/vision/datasets/mnist.py. Reads idx/gz files when
    `image_path`/`label_path` given; otherwise synthetic 28x28."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if image_path or label_path:
            # explicit paths are authoritative: fail loudly, never silently
            # substitute cache/synthetic data for what the user asked for
            if not (image_path and label_path):
                raise ValueError(
                    "MNIST: give BOTH image_path and label_path (or "
                    "neither, to probe the dataset cache home)")
            for pth in (image_path, label_path):
                if not os.path.exists(pth):
                    raise FileNotFoundError(f"MNIST: {pth} does not exist")
        else:
            # canonical filenames in the standard cache home (what the
            # reference's downloader leaves behind)
            stem = "train" if mode == "train" else "t10k"
            base = os.path.join(data_home(), self.NAME)
            for suff in (".gz", ""):
                ip = os.path.join(base, f"{stem}-images-idx3-ubyte{suff}")
                lp = os.path.join(base, f"{stem}-labels-idx1-ubyte{suff}")
                if os.path.exists(ip) and os.path.exists(lp):
                    image_path, label_path = ip, lp
                    break
        if image_path and label_path:
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        else:
            n = 60000 if mode == "train" else 10000
            n = min(n, 4096)  # synthetic: keep small
            synth = _SyntheticImageDataset(n, (1, 28, 28), 10,
                                           seed=0 if mode == "train" else 1)
            self.images = np.stack([synth[i][0] for i in range(n)])
            self.labels = np.asarray([synth[i][1] for i in range(n)])

    @staticmethod
    def _read_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return (data.reshape(n, 1, rows, cols).astype(np.float32) / 255.0)

    @staticmethod
    def _read_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """ref python/paddle/dataset/cifar.py: the binary-batches format —
    per record 1 label byte (2 for cifar-100: coarse+fine) + 3072 image
    bytes (RGB planes, 32x32). Reads extracted *-batches-bin dirs or the
    distribution tar.gz; synthetic fallback when neither exists."""

    NUM_CLASSES = 10
    DIRNAME = "cifar-10-batches-bin"
    TRAIN_FILES = [f"data_batch_{i}.bin" for i in range(1, 6)]
    TEST_FILES = ["test_batch.bin"]
    LABEL_BYTES = 1

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        imgs, labels = self._load_real(data_file, mode)
        if imgs is None:
            synth = _SyntheticImageDataset(
                1024, (3, 32, 32), self.NUM_CLASSES,
                seed=0 if mode == "train" else 1)
            imgs = np.stack([synth[i][0] for i in range(len(synth))])
            labels = np.asarray([synth[i][1] for i in range(len(synth))])
        self.images, self.labels = imgs, labels

    # ------------------------------------------------------------- real IO
    def _load_real(self, data_file, mode):
        names = self.TRAIN_FILES if mode == "train" else self.TEST_FILES
        base = os.path.join(data_home(), "cifar", self.DIRNAME)
        if data_file:
            # explicit file is authoritative: fail loudly rather than
            # silently training on cache/synthetic data
            if not os.path.exists(data_file):
                raise FileNotFoundError(
                    f"Cifar: {data_file} does not exist")
            if not data_file.endswith((".tar.gz", ".tgz")):
                raise ValueError(
                    f"Cifar: expected a .tar.gz distribution archive, "
                    f"got {data_file}")
            blobs = []
            with tarfile.open(data_file, "r:gz") as tf:
                for m in tf.getmembers():
                    if os.path.basename(m.name) in names:
                        blobs.append(tf.extractfile(m).read())
            if not blobs:
                raise ValueError(
                    f"Cifar: no {names} members inside {data_file}")
            return self._parse(b"".join(blobs))
        paths = [os.path.join(base, n) for n in names]
        if all(os.path.exists(p) for p in paths):
            return self._parse(b"".join(open(p, "rb").read()
                                        for p in paths))
        return None, None

    def _parse(self, blob):
        rec = self.LABEL_BYTES + 3072
        n = len(blob) // rec
        arr = np.frombuffer(blob[:n * rec], np.uint8).reshape(n, rec)
        labels = arr[:, self.LABEL_BYTES - 1].astype(np.int64)  # fine label
        # keep uint8 resident (a real CIFAR train split is ~150MB; float32
        # would 4x it) — items convert on access
        imgs = arr[:, self.LABEL_BYTES:].reshape(n, 3, 32, 32).copy()
        return imgs, labels

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100
    DIRNAME = "cifar-100-binary"
    TRAIN_FILES = ["train.bin"]
    TEST_FILES = ["test.bin"]
    LABEL_BYTES = 2     # coarse + fine; fine is authoritative


# --------------------------------------------------------------------------
# folder datasets (ref python/paddle/vision/datasets/folder.py): REAL image
# decoding via PIL over class-per-directory trees — the generic "bring your
# own images" path that needs no downloads
# --------------------------------------------------------------------------

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    from PIL import Image
    with Image.open(path) as img:
        return np.asarray(img.convert("RGB"))


class DatasetFolder(Dataset):
    """class-per-subdirectory image tree -> (image, class_index) samples
    (ref folder.py DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        extensions = tuple(extensions or IMG_EXTENSIONS)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"DatasetFolder: no class dirs under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    path = os.path.join(dirpath, fn)
                    ok = (is_valid_file(path) if is_valid_file
                          else fn.lower().endswith(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"DatasetFolder: no images under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(target)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """flat (recursive) image list, no labels (ref folder.py ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        extensions = tuple(extensions or IMG_EXTENSIONS)
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                path = os.path.join(dirpath, fn)
                ok = (is_valid_file(path) if is_valid_file
                      else fn.lower().endswith(extensions))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise ValueError(f"ImageFolder: no images under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """ref datasets/flowers.py (102-category). Three real-data paths:
    the REAL archive triplet (102flowers.tgz + imagelabels.mat +
    setid.mat — parsed exactly like the reference, including its
    train<->tstid flag swap), a class-per-dir tree in the cache home,
    or the synthetic 3x64x64 fallback (zero-egress)."""

    # ref flowers.py MODE_FLAG_MAP: "test data is more than train data"
    MODE_FLAG_MAP = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        self.backend = backend
        if data_file or label_file or setid_file:
            if not (data_file and label_file and setid_file):
                raise ValueError(
                    "Flowers real-archive mode needs ALL of data_file "
                    "(102flowers.tgz), label_file (imagelabels.mat) and "
                    "setid_file (setid.mat) — the zero-egress build "
                    "cannot download the missing pieces")
            self._init_real_archives(data_file, label_file, setid_file,
                                     mode)
            return
        root = os.path.join(data_home(), "flowers")
        if os.path.isdir(root) and any(
                os.path.isdir(os.path.join(root, d))
                for d in os.listdir(root) if not d.startswith(".")):
            folder = DatasetFolder(root, transform=transform)
            # deterministic 80/20 split by sample index (the reference
            # splits via setid.mat; without it train/test must still be
            # DISJOINT or evaluation leaks the training set)
            keep = (0, 1, 2, 3) if mode == "train" else (4,)
            folder.samples = [sm for i, sm in enumerate(folder.samples)
                              if i % 5 in keep]
            self._folder = folder
            self.images = self.labels = None
        else:
            self._folder = None
            synth = _SyntheticImageDataset(
                512, (3, 64, 64), 102, seed=0 if mode == "train" else 1)
            self.images = np.stack([synth[i][0] for i in range(len(synth))])
            self.labels = np.asarray([synth[i][1]
                                      for i in range(len(synth))])

    # ---- real-archive path (ref flowers.py:122-160)
    def _init_real_archives(self, data_file, label_file, setid_file, mode):
        import tarfile
        import scipy.io as scio
        self._folder = None
        self.images = self.labels = None
        self._tar = tarfile.open(data_file)
        self._name2mem = {m.name: m for m in self._tar.getmembers()}
        self._mat_labels = scio.loadmat(label_file)["labels"][0]
        self._indexes = scio.loadmat(setid_file)[
            self.MODE_FLAG_MAP[mode.lower()]][0]

    def _real_archive_item(self, idx):
        import io as _io
        from PIL import Image
        index = int(self._indexes[idx])
        label = np.array([self._mat_labels[index - 1]])
        raw = self._tar.extractfile(
            self._name2mem["jpg/image_%05d.jpg" % index]).read()
        image = Image.open(_io.BytesIO(raw))
        if self.backend != "pil":
            image = np.array(image)
        if self.transform is not None:
            image = self.transform(image)
        if self.backend == "pil":
            return image, label.astype("int64")
        return np.asarray(image, dtype="float32"), label.astype("int64")

    def __getitem__(self, idx):
        if getattr(self, "_indexes", None) is not None:
            return self._real_archive_item(idx)
        if self._folder is not None:
            return self._folder[idx]
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        if getattr(self, "_indexes", None) is not None:
            return len(self._indexes)
        return (len(self._folder) if self._folder is not None
                else len(self.images))


class VOC2012(Dataset):
    """ref datasets/voc2012.py (segmentation pairs). Real VOCdevkit layout
    when present in the cache home; synthetic (image, mask) fallback."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        base = os.path.join(data_home(), "voc2012", "VOCdevkit", "VOC2012")
        lst = os.path.join(base, "ImageSets", "Segmentation",
                           ("train" if mode == "train" else "val") + ".txt")
        if os.path.exists(lst):
            names = [l.strip() for l in open(lst) if l.strip()]
            self._pairs = [
                (os.path.join(base, "JPEGImages", n + ".jpg"),
                 os.path.join(base, "SegmentationClass", n + ".png"))
                for n in names]
        else:
            self._pairs = None
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self._imgs = rng.rand(64, 3, 32, 32).astype("f4")
            self._masks = rng.randint(0, 21, (64, 32, 32)).astype("i8")

    def __getitem__(self, idx):
        if self._pairs is not None:
            img_p, mask_p = self._pairs[idx]
            img = _default_loader(img_p)
            from PIL import Image
            with Image.open(mask_p) as m:
                mask = np.asarray(m, dtype=np.int64)
        else:
            img, mask = self._imgs[idx], self._masks[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return (len(self._pairs) if self._pairs is not None
                else len(self._imgs))
