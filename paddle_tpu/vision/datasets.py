"""Dataset zoo (ref python/paddle/vision/datasets: MNIST, Cifar10/100,
FashionMNIST + paddle/dataset loaders). This environment has zero egress, so
every dataset supports `backend='synthetic'` generation with deterministic
labels; file-based loading is used when local files exist."""
import gzip
import os
import struct

import numpy as np

from ..io import Dataset


class _SyntheticImageDataset(Dataset):
    """Deterministic fake data with learnable signal: class-dependent mean
    patterns so convergence tests exercise real learning."""

    def __init__(self, num_samples, image_shape, num_classes, transform=None,
                 seed=0, pattern_seed=1234):
        self.num_samples = num_samples
        self.image_shape = image_shape
        self.num_classes = num_classes
        self.transform = transform
        # class patterns are split-independent (train and test must share the
        # underlying "digit shapes"); `seed` only varies the noise + labels
        self._patterns = np.random.RandomState(pattern_seed).rand(
            num_classes, *image_shape).astype(np.float32)
        rng = np.random.RandomState(seed)
        self._labels = rng.randint(0, num_classes, num_samples)
        self._seed = seed * 1_000_003

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seed + idx + 1)
        label = self._labels[idx]
        img = (self._patterns[label]
               + 0.3 * rng.randn(*self.image_shape).astype(np.float32))
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), np.int64(label)

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """ref python/paddle/vision/datasets/mnist.py. Reads idx/gz files when
    `image_path`/`label_path` given; otherwise synthetic 28x28."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if image_path and label_path and os.path.exists(image_path):
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        else:
            n = 60000 if mode == "train" else 10000
            n = min(n, 4096)  # synthetic: keep small
            synth = _SyntheticImageDataset(n, (1, 28, 28), 10,
                                           seed=0 if mode == "train" else 1)
            self.images = np.stack([synth[i][0] for i in range(n)])
            self.labels = np.asarray([synth[i][1] for i in range(n)])

    @staticmethod
    def _read_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return (data.reshape(n, 1, rows, cols).astype(np.float32) / 255.0)

    @staticmethod
    def _read_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        n = 1024
        self._synth = _SyntheticImageDataset(
            n, (3, 32, 32), 10, seed=0 if mode == "train" else 1)

    def __getitem__(self, idx):
        img, label = self._synth[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self._synth)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        self._synth = _SyntheticImageDataset(
            1024, (3, 32, 32), 100, seed=0 if mode == "train" else 1)
