"""Vision transforms (ref python/paddle/vision/transforms): numpy/host-side;
compose-based. Images are HWC numpy arrays (uint8 or float)."""
import numbers

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    """HWC uint8 -> CHW float32 in [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        n = arr.shape[0 if self.data_format == "CHW" else -1]
        mean = self.mean[:n]
        std = self.std[:n]
        if self.data_format == "CHW":
            return (arr - mean[:, None, None]) / std[:, None, None]
        return (arr - mean) / std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        import jax
        import jax.numpy as jnp
        hwc = arr.ndim == 3
        target = self.size + ((arr.shape[2],) if hwc else ())
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), target, "linear")
        out = np.asarray(out)
        return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pad = ((p, p), (p, p)) + (((0, 0),) if arr.ndim == 3 else ())
            arr = np.pad(arr, pad)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self.scale) * area
            aspect = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                              np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target_area * aspect)))
            th = int(round(np.sqrt(target_area / aspect)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return self._resize(arr[i:i + th, j:j + tw])
        return self._resize(CenterCrop(min(h, w))(arr))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(arr * alpha, 0, 255).astype(np.asarray(img).dtype)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()
