"""Vision transforms (ref python/paddle/vision/transforms): numpy/host-side;
compose-based. Images are HWC numpy arrays (uint8 or float)."""
import numbers

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    """HWC uint8 -> CHW float32 in [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        n = arr.shape[0 if self.data_format == "CHW" else -1]
        mean = self.mean[:n]
        std = self.std[:n]
        if self.data_format == "CHW":
            return (arr - mean[:, None, None]) / std[:, None, None]
        return (arr - mean) / std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        import jax
        import jax.numpy as jnp
        hwc = arr.ndim == 3
        target = self.size + ((arr.shape[2],) if hwc else ())
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), target, "linear")
        out = np.asarray(out)
        return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pad = ((p, p), (p, p)) + (((0, 0),) if arr.ndim == 3 else ())
            arr = np.pad(arr, pad)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self.scale) * area
            aspect = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                              np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target_area * aspect)))
            th = int(round(np.sqrt(target_area / aspect)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return self._resize(arr[i:i + th, j:j + tw])
        return self._resize(CenterCrop(min(h, w))(arr))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(arr * alpha, 0, 255).astype(np.asarray(img).dtype)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


# ------------------------------------------------------------ functional tail
# (ref python/paddle/vision/transforms/functional.py — host-side numpy)

def adjust_brightness(img, factor):
    """Blend with black: out = img * factor (clipped for uint8)."""
    arr = np.asarray(img)
    out = arr.astype(np.float32) * float(factor)
    return (np.clip(out, 0, 255).astype(np.uint8)
            if arr.dtype == np.uint8 else out)


def adjust_contrast(img, factor):
    """Blend with the GRAYSCALE mean of the image (0.299/0.587/0.114
    weights, matching the reference/PIL), not the raw channel mean."""
    arr = np.asarray(img)
    f = arr.astype(np.float32)
    if f.ndim == 3 and f.shape[-1] >= 3:
        gray = (0.299 * f[..., 0] + 0.587 * f[..., 1] + 0.114 * f[..., 2])
        mean = gray.mean()
    else:
        mean = f.mean()
    out = mean + (f - mean) * float(factor)
    return (np.clip(np.round(out), 0, 255).astype(np.uint8)
            if arr.dtype == np.uint8 else out)


def adjust_saturation(img, factor):
    """Blend with the grayscale version (HWC, 3 channels)."""
    arr = np.asarray(img)
    f = arr.astype(np.float32)
    gray = (0.299 * f[..., 0] + 0.587 * f[..., 1]
            + 0.114 * f[..., 2])[..., None]
    out = gray + (f - gray) * float(factor)
    return (np.clip(out, 0, 255).astype(np.uint8)
            if arr.dtype == np.uint8 else out)


def adjust_hue(img, factor):
    """Rotate hue by factor in [-0.5, 0.5] (HWC uint8/float RGB)."""
    arr = np.asarray(img)
    f = arr.astype(np.float32) / (255.0 if arr.dtype == np.uint8 else 1.0)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    maxc = f[..., :3].max(-1)
    minc = f[..., :3].min(-1)
    v = maxc
    c = maxc - minc
    s = np.where(maxc > 0, c / np.maximum(maxc, 1e-12), 0.0)
    rc = np.where(c > 0, (maxc - r) / np.maximum(c, 1e-12), 0.0)
    gc = np.where(c > 0, (maxc - g) / np.maximum(c, 1e-12), 0.0)
    bc = np.where(c > 0, (maxc - b) / np.maximum(c, 1e-12), 0.0)
    h = np.where(r == maxc, bc - gc,
                 np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc)) / 6.0
    h = (h + float(factor)) % 1.0
    i = np.floor(h * 6.0)
    fr = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * fr)
    t = v * (1.0 - s * (1.0 - fr))
    i = (i.astype(np.int32) % 6)[..., None]
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    if arr.dtype == np.uint8:
        return np.clip(np.round(out * 255.0), 0, 255).astype(np.uint8)
    return out


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img)
    f = arr.astype(np.float32)
    gray = 0.299 * f[..., 0] + 0.587 * f[..., 1] + 0.114 * f[..., 2]
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return (np.clip(np.round(out), 0, 255).astype(np.uint8)
            if arr.dtype == np.uint8 else out)


def rotate(img, angle, center=None, fill=0):
    """Rotate counter-clockwise by `angle` degrees about the center
    (nearest-neighbor, same output size — ref F.rotate defaults)."""
    arr = np.asarray(img)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    yy, xx = np.mgrid[0:h, 0:w]
    # inverse map: output pixel -> source pixel
    xs = cos * (xx - cx) + sin * (yy - cy) + cx
    ys = -sin * (xx - cx) + cos * (yy - cy) + cy
    xi = np.round(xs).astype(np.int64)
    yi = np.round(ys).astype(np.int64)
    valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    out = np.full_like(arr, fill)
    out[valid] = arr[yi[valid], xi[valid]]
    return out


class ContrastTransform(BaseTransform):
    """ref transforms.ContrastTransform: random contrast in
    [1-value, 1+value]."""

    def __init__(self, value=0.4):
        self.value = float(value)

    def _apply_image(self, img):
        f = 1.0 + np.random.uniform(-self.value, self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value=0.4):
        self.value = float(value)

    def _apply_image(self, img):
        f = 1.0 + np.random.uniform(-self.value, self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value=0.1):
        self.value = float(value)

    def _apply_image(self, img):
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """ref transforms.ColorJitter: composes the per-property random
    transforms (Brightness/Contrast/Saturation/Hue) in random order —
    one place owns each property's jitter convention."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self._ops = []
        if brightness:
            self._ops.append(BrightnessTransform(float(brightness)))
        if contrast:
            self._ops.append(ContrastTransform(float(contrast)))
        if saturation:
            self._ops.append(SaturationTransform(float(saturation)))
        if hue:
            self._ops.append(HueTransform(float(hue)))

    def _apply_image(self, img):
        order = np.random.permutation(len(self._ops))
        for i in order:
            img = self._ops[i]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    """ref transforms.Pad: constant pad on HWC images."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, numbers.Number):
            padding = (padding,) * 4
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding            # left, top, right, bottom
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = np.asarray(img)
        l, t, r, b = self.padding
        spec = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
        if self.padding_mode == "constant":
            return np.pad(arr, spec, constant_values=self.fill)
        return np.pad(arr, spec, mode=self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, fill=0):
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, fill=self.fill)


class RandomErasing(BaseTransform):
    """ref transforms.RandomErasing over CHW/HWC float or uint8."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        arr = np.asarray(img)
        if np.random.rand() > self.prob:
            return arr
        # layout: HWC when the last dim looks like channels, else CHW
        hwc = arr.ndim == 2 or arr.shape[-1] in (1, 3, 4)
        h, w = arr.shape[:2] if hwc else arr.shape[1:3]
        arr = arr.copy()
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                y = np.random.randint(0, h - eh)
                x = np.random.randint(0, w - ew)
                if hwc:
                    arr[y:y + eh, x:x + ew] = self.value
                else:
                    arr[:, y:y + eh, x:x + ew] = self.value
                return arr
        return arr


def crop(img, top, left, height, width):
    """ref transforms/functional.py crop: CHW or HWC numpy image."""
    import numpy as _np
    img = _np.asarray(img)
    if img.ndim == 3 and img.shape[0] in (1, 3):     # CHW
        return img[:, top:top + height, left:left + width]
    return img[top:top + height, left:left + width]


def center_crop(img, output_size):
    import numpy as _np
    img = _np.asarray(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    th, tw = output_size
    if img.ndim == 3 and img.shape[0] in (1, 3):
        h, w = img.shape[1], img.shape[2]
    else:
        h, w = img.shape[0], img.shape[1]
    return crop(img, (h - th) // 2, (w - tw) // 2, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    """ref transforms/functional.py pad: int | (pl,pt) | (pl,pt,pr,pb)."""
    import numpy as _np
    img = _np.asarray(img)
    if isinstance(padding, int):
        pl = pt_ = pr = pb = padding
    elif len(padding) == 2:
        pl, pt_ = padding
        pr, pb = padding
    else:
        pl, pt_, pr, pb = padding
    chw = img.ndim == 3 and img.shape[0] in (1, 3)
    if chw:
        cfg = [(0, 0), (pt_, pb), (pl, pr)]
    elif img.ndim == 3:
        cfg = [(pt_, pb), (pl, pr), (0, 0)]
    else:
        cfg = [(pt_, pb), (pl, pr)]
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    if mode == "constant":
        return _np.pad(img, cfg, mode=mode, constant_values=fill)
    return _np.pad(img, cfg, mode=mode)
