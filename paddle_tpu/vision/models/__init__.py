"""Vision model zoo (ref python/paddle/vision/models)."""
from .lenet import LeNet
from .resnet import (ResNet, BasicBlock, BottleneckBlock,
                     resnet18, resnet34, resnet50, resnet101, resnet152)
from .vgg import VGG, make_layers, vgg11, vgg13, vgg16, vgg19
from .mobilenet import (MobileNetV1, MobileNetV2, ConvBNLayer,
                        DepthwiseSeparable, InvertedResidual,
                        mobilenet_v1, mobilenet_v2)

# ref mobilenetv2 exports ConvBNReLU; this zoo's equivalent fused block
ConvBNReLU = ConvBNLayer
