"""paddle.vision.ops — detection operator set
(ref python/paddle/vision/ops.py + paddle/fluid/operators/detection/:
iou_similarity_op, box_coder_op, prior_box_op, yolo_box_op, nms util,
roi_align_op).

TPU discipline: every op is fixed-shape. NMS returns a fixed-size keep MASK
(scores of suppressed boxes are zeroed) computed by an O(n) lax.fori_loop of
vectorised suppressions instead of the reference's dynamic output list —
callers slice top-k afterwards, which is how detection heads compose on
XLA. roi_align is gather+bilinear arithmetic (no custom kernel needed; XLA
fuses it).
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..framework.tensor import Tensor
from ..ops.dispatch import apply, as_array, register_op


# ------------------------------------------------------------------- iou

def _box_iou_raw(a, b):
    """a: [N, 4], b: [M, 4] (x1, y1, x2, y2) -> [N, M] IoU."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * \
        jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * \
        jnp.maximum(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


register_op("box_iou", _box_iou_raw)


def box_iou(boxes1, boxes2, name=None):
    return apply(_box_iou_raw, (boxes1, boxes2), name="box_iou")


iou_similarity = box_iou        # ref detection/iou_similarity_op.cc


# ------------------------------------------------------------------- nms

def _nms_raw(boxes, scores, iou_threshold=0.5, score_threshold=None):
    """Greedy NMS as a fixed-shape suppression mask (1 = kept).
    O(N) sequential rounds, each suppressing against the best live box."""
    n = boxes.shape[0]
    iou = _box_iou_raw(boxes, boxes)
    live = jnp.ones((n,), bool)
    if score_threshold is not None:
        live = live & (scores > score_threshold)
    kept = jnp.zeros((n,), bool)

    def body(_, carry):
        live, kept = carry
        masked = jnp.where(live, scores, -jnp.inf)
        best = jnp.argmax(masked)
        any_live = jnp.any(live)
        take = live[best] & any_live
        kept = kept.at[best].set(take | kept[best])
        # suppress neighbours of the chosen box (and itself)
        suppress = (iou[best] >= iou_threshold) | \
            (jnp.arange(n) == best)
        live = live & jnp.where(take, ~suppress, True)
        return live, kept

    _, kept = lax.fori_loop(0, n, body, (live, kept))
    return kept


register_op("nms", _nms_raw)


def nms(boxes, scores, iou_threshold=0.5, score_threshold=None, top_k=None,
        name=None):
    """ref python/paddle/vision/ops.py nms — returns kept indices sorted by
    score (fixed count when top_k given; else a dynamic-size host slice)."""
    kept = apply(_nms_raw, (boxes, scores),
                 {"iou_threshold": float(iou_threshold),
                  "score_threshold": None if score_threshold is None
                  else float(score_threshold)},
                 differentiable=False, name="nms")
    k = as_array(kept)
    s = as_array(scores)
    ranked = jnp.argsort(jnp.where(k, s, -jnp.inf))[::-1]
    n_kept = jnp.sum(k)
    if top_k is not None:
        # fixed shape: positions past the kept count are -1, never a
        # suppressed box's real index
        idx = jnp.where(jnp.arange(int(top_k)) < n_kept,
                        ranked[:top_k], -1)
        return Tensor(idx)
    n_keep = int(np.asarray(n_kept))            # host sync: dynamic count
    return Tensor(ranked[:n_keep])


# --------------------------------------------------------------- box_coder

def _box_coder_raw(prior_box, prior_box_var, target_box,
                   code_type="encode_center_size", box_normalized=True,
                   axis=0):
    """ref detection/box_coder_op.h: encode/decode between corner boxes and
    center-size offsets."""
    norm = 0.0 if box_normalized else 1.0
    pw = prior_box[:, 2] - prior_box[:, 0] + norm
    ph = prior_box[:, 3] - prior_box[:, 1] + norm
    px = prior_box[:, 0] + pw * 0.5
    py = prior_box[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((4,), target_box.dtype)
    else:
        var = prior_box_var
    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0] + norm
        th = target_box[:, 3] - target_box[:, 1] + norm
        tx = target_box[:, 0] + tw * 0.5
        ty = target_box[:, 1] + th * 0.5
        out = jnp.stack([
            (tx - px) / pw, (ty - py) / ph,
            jnp.log(jnp.maximum(tw / pw, 1e-10)),
            jnp.log(jnp.maximum(th / ph, 1e-10))], axis=1)
        return out / (var if var.ndim == 1 else var)
    # decode: target_box holds offsets [N, 4]
    off = target_box * (var if var.ndim == 1 else var)
    ox = off[:, 0] * pw + px
    oy = off[:, 1] * ph + py
    ow = jnp.exp(off[:, 2]) * pw
    oh = jnp.exp(off[:, 3]) * ph
    return jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                      ox + ow * 0.5 - norm, oy + oh * 0.5 - norm], axis=1)


register_op("box_coder", _box_coder_raw)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    if prior_box_var is None:
        return apply(lambda p, t, **kw: _box_coder_raw(p, None, t, **kw),
                     (prior_box, target_box),
                     {"code_type": code_type,
                      "box_normalized": bool(box_normalized)},
                     name="box_coder")
    return apply(_box_coder_raw, (prior_box, prior_box_var, target_box),
                 {"code_type": code_type,
                  "box_normalized": bool(box_normalized)}, name="box_coder")


# --------------------------------------------------------------- prior_box

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    """SSD prior boxes (ref detection/prior_box_op.cc). Host-side numpy —
    priors are data-independent constants per feature-map shape."""
    in_h, in_w = as_array(input).shape[-2:]
    img_h, img_w = as_array(image).shape[-2:]
    step_w = steps[0] or img_w / in_w
    step_h = steps[1] or img_h / in_h
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    boxes = []
    for y in range(in_h):
        for x in range(in_w):
            cx = (x + offset) * step_w
            cy = (y + offset) * step_h
            for k, ms in enumerate(min_sizes):
                for ar in ars:
                    bw = ms * np.sqrt(ar) * 0.5
                    bh = ms / np.sqrt(ar) * 0.5
                    boxes.append([(cx - bw) / img_w, (cy - bh) / img_h,
                                  (cx + bw) / img_w, (cy + bh) / img_h])
                if max_sizes:
                    s = np.sqrt(ms * max_sizes[k]) * 0.5
                    boxes.append([(cx - s) / img_w, (cy - s) / img_h,
                                  (cx + s) / img_w, (cy + s) / img_h])
    out = np.asarray(boxes, np.float32).reshape(in_h, in_w, -1, 4)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


# --------------------------------------------------------------- yolo_box

def _yolo_box_raw(x, img_size, anchors=(), class_num=1, conf_thresh=0.01,
                  downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """ref detection/yolo_box_op.h — decode YOLOv3 head output [N, C, H, W]
    into boxes [N, H*W*na, 4] + scores [N, H*W*na, class_num]."""
    n, c, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, x.dtype).reshape(na, 2)
    x = x.reshape(n, na, 5 + class_num, h, w)
    gx = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
    bx = (jax.nn.sigmoid(x[:, :, 0]) * alpha + beta + gx) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) * alpha + beta + gy) / h
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / \
        (w * downsample_ratio)
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / \
        (h * downsample_ratio)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    img_h = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    img_w = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    x1 = (bx - bw * 0.5) * img_w
    y1 = (by - bh * 0.5) * img_h
    x2 = (bx + bw * 0.5) * img_w
    y2 = (by + bh * 0.5) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, -1, 4)
    mask = (conf > conf_thresh)[..., None]
    scores = jnp.where(mask, probs.transpose(0, 1, 3, 4, 2),
                       0.0).reshape(n, -1, class_num)
    return boxes, scores


register_op("yolo_box", _yolo_box_raw)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, name=None,
             scale_x_y=1.0):
    boxes, scores = apply(
        _yolo_box_raw, (x, img_size),
        {"anchors": tuple(int(a) for a in anchors),
         "class_num": int(class_num), "conf_thresh": float(conf_thresh),
         "downsample_ratio": int(downsample_ratio),
         "clip_bbox": bool(clip_bbox), "scale_x_y": float(scale_x_y)},
        name="yolo_box")
    return boxes, scores


# --------------------------------------------------------------- roi_align

def _roi_align_raw(x, boxes, boxes_num=None, output_size=(1, 1),
                   spatial_scale=1.0, sampling_ratio=-1, aligned=True):
    """ref roi_align_op.h: bilinear-sampled average pooling per RoI.
    x: [N, C, H, W]; boxes: [R, 4] in input coords; boxes are all on image 0
    when boxes_num is None (single-image path used by the test suite)."""
    n, c, h, w = x.shape
    ph, pw = output_size
    off = 0.5 if aligned else 0.0
    img = x[0]                                    # [C, H, W]

    def one_roi(box):
        x1, y1, x2, y2 = box * spatial_scale
        x1, y1 = x1 - off, y1 - off
        x2, y2 = x2 - off, y2 - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-3)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-3)
        bin_w = rw / pw
        bin_h = rh / ph
        s = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [ph, pw, s, s, 2]
        iy = (jnp.arange(ph)[:, None] * bin_h + y1 +
              (jnp.arange(s)[None, :] + 0.5) * bin_h / s)   # [ph, s]
        ix = (jnp.arange(pw)[:, None] * bin_w + x1 +
              (jnp.arange(s)[None, :] + 0.5) * bin_w / s)   # [pw, s]

        def bilinear(yy, xx):
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            y0i, x0i, y1i, x1i = (y0.astype(int), x0.astype(int),
                                  y1_.astype(int), x1_.astype(int))
            v00 = img[:, y0i, x0i]
            v01 = img[:, y0i, x1i]
            v10 = img[:, y1i, x0i]
            v11 = img[:, y1i, x1i]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                    v10 * wy * (1 - wx) + v11 * wy * wx)

        # average over the s*s samples in each bin
        vals = jax.vmap(lambda yy: jax.vmap(
            lambda xx: bilinear(yy, xx))(ix.ravel()))(iy.ravel())
        # vals: [ph*s, pw*s, C] -> [ph, s, pw, s, C] -> mean samples
        vals = vals.reshape(ph, s, pw, s, c).mean(axis=(1, 3))
        return vals.transpose(2, 0, 1)            # [C, ph, pw]

    return jax.vmap(one_roi)(boxes)


register_op("roi_align", _roi_align_raw)


def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    from ..ops.dispatch import as_array as _aa
    if boxes_num is not None or _aa(x).shape[0] != 1:
        raise NotImplementedError(
            "roi_align: multi-image batches (boxes_num) not supported yet — "
            "pass one image per call (vmap over images for batches)")
    return apply(_roi_align_raw, (x, boxes),
                 {"output_size": tuple(output_size),
                  "spatial_scale": float(spatial_scale),
                  "sampling_ratio": int(sampling_ratio),
                  "aligned": bool(aligned)}, name="roi_align")


# --------------------------------------------------------- roi pool family

def _roi_pool_raw(x, boxes, output_size=(1, 1), spatial_scale=1.0):
    """Quantized max-pool ROI pooling (ref operators/roi_pool_op.cc): bin
    boundaries floor/ceil'd to integer pixels, max over each bin. Computed
    as masked maxes over the full map per bin — static shapes for XLA; the
    perf path for detection heads is roi_align. x: [1, C, H, W],
    boxes: [R, 4] -> [R, C, ph, pw]."""
    import jax
    import jax.numpy as jnp
    ph, pw = output_size
    img = x[0]
    c, h, w = img.shape
    ys = jnp.arange(h)
    xs = jnp.arange(w)

    def one_roi(box):
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bh, bw = rh / ph, rw / pw

        def one_bin(i, j):
            hs = jnp.floor(y1 + i * bh)
            he = jnp.ceil(y1 + (i + 1) * bh)
            ws_ = jnp.floor(x1 + j * bw)
            we = jnp.ceil(x1 + (j + 1) * bw)
            m = ((ys[:, None] >= hs) & (ys[:, None] < he) &
                 (xs[None, :] >= ws_) & (xs[None, :] < we))
            neg = jnp.finfo(img.dtype).min
            vals = jnp.where(m[None], img, neg)
            mx = jnp.max(vals, axis=(1, 2))
            any_m = jnp.any(m)
            return jnp.where(any_m, mx, 0.0)

        ii, jj = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw), indexing="ij")
        bins = jax.vmap(jax.vmap(one_bin))(ii, jj)   # [ph, pw, C]
        return bins.transpose(2, 0, 1)

    return jax.vmap(one_roi)(boxes)


register_op("roi_pool", _roi_pool_raw)


def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
             name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    from ..ops.dispatch import as_array as _aa
    if boxes_num is not None or _aa(x).shape[0] != 1:
        raise NotImplementedError(
            "roi_pool: pass one image per call (vmap over images for batches)")
    return apply(_roi_pool_raw, (x, boxes),
                 {"output_size": tuple(output_size),
                  "spatial_scale": float(spatial_scale)}, name="roi_pool")


def _psroi_pool_raw(x, boxes, output_size=(1, 1), spatial_scale=1.0,
                    output_channels=1):
    """Position-sensitive ROI average pooling (ref operators/psroi_pool_op.cc):
    input channels C = output_channels*ph*pw; bin (i,j) of output channel k
    averages input channel k*ph*pw + i*pw + j over the bin's pixels."""
    import jax
    import jax.numpy as jnp
    ph, pw = output_size
    img = x[0]
    c, h, w = img.shape
    ys = jnp.arange(h)
    xs = jnp.arange(w)

    def one_roi(box):
        x1 = jnp.round(box[0]) * spatial_scale
        y1 = jnp.round(box[1]) * spatial_scale
        x2 = jnp.round(box[2] + 1.0) * spatial_scale
        y2 = jnp.round(box[3] + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bh, bw = rh / ph, rw / pw

        def one_bin(i, j, k):
            hs = jnp.floor(y1 + i * bh)
            he = jnp.ceil(y1 + (i + 1) * bh)
            ws_ = jnp.floor(x1 + j * bw)
            we = jnp.ceil(x1 + (j + 1) * bw)
            m = ((ys[:, None] >= hs) & (ys[:, None] < he) &
                 (xs[None, :] >= ws_) & (xs[None, :] < we))
            ch = (k * ph + i) * pw + j
            plane = img[ch]
            s = jnp.sum(jnp.where(m, plane, 0.0))
            n = jnp.sum(m)
            return jnp.where(n > 0, s / jnp.maximum(n, 1), 0.0)

        kk, ii, jj = jnp.meshgrid(jnp.arange(output_channels),
                                  jnp.arange(ph), jnp.arange(pw),
                                  indexing="ij")
        return jax.vmap(jax.vmap(jax.vmap(
            lambda k, i, j: one_bin(i, j, k))))(kk, ii, jj)

    return jax.vmap(one_roi)(boxes)


register_op("psroi_pool", _psroi_pool_raw)


def psroi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
               name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    from ..ops.dispatch import as_array as _aa
    xa = _aa(x)
    if boxes_num is not None or xa.shape[0] != 1:
        raise NotImplementedError(
            "psroi_pool: pass one image per call")
    ph, pw = output_size
    if xa.shape[1] % (ph * pw) != 0:
        raise ValueError(
            f"psroi_pool: input channels ({xa.shape[1]}) must be divisible "
            f"by output_size h*w ({ph}*{pw}) — ref psroi_pool_op enforces "
            f"input_channels == output_channels * ph * pw")
    oc = xa.shape[1] // (ph * pw)
    return apply(_psroi_pool_raw, (x, boxes),
                 {"output_size": tuple(output_size),
                  "spatial_scale": float(spatial_scale),
                  "output_channels": int(oc)}, name="psroi_pool")


# ------------------------------------------------- channel/space reshapes

def _affine_channel_raw(x, scale, bias, data_layout="NCHW"):
    """ref operators/affine_channel_op.cc: y = x * scale[c] + bias[c]."""
    import jax.numpy as jnp
    if data_layout == "NCHW":
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        shape = (1,) * (x.ndim - 1) + (-1,)
    return x * scale.reshape(shape) + bias.reshape(shape)


register_op("affine_channel", _affine_channel_raw)


def affine_channel(x, scale, bias, data_layout="NCHW", name=None):
    return apply(_affine_channel_raw, (x, scale, bias),
                 {"data_layout": data_layout}, name="affine_channel")


def _channel_shuffle_raw(x, groups=1, data_format="NCHW"):
    """ref operators/shuffle_channel_op.cc / paddle 2.x channel_shuffle."""
    import jax.numpy as jnp
    if data_format == "NCHW":
        b, c, h, w = x.shape
        return (x.reshape(b, groups, c // groups, h, w)
                 .transpose(0, 2, 1, 3, 4).reshape(b, c, h, w))
    b, h, w, c = x.shape
    return (x.reshape(b, h, w, groups, c // groups)
             .transpose(0, 1, 2, 4, 3).reshape(b, h, w, c))


register_op("channel_shuffle", _channel_shuffle_raw)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    return apply(_channel_shuffle_raw, (x,),
                 {"groups": int(groups), "data_format": data_format},
                 name="channel_shuffle")


def _pixel_unshuffle_raw(x, downscale_factor=1, data_format="NCHW"):
    """Inverse of pixel_shuffle (ref operators/pixel_unshuffle_op.cc)."""
    r = downscale_factor
    if data_format == "NCHW":
        b, c, h, w = x.shape
        return (x.reshape(b, c, h // r, r, w // r, r)
                 .transpose(0, 1, 3, 5, 2, 4).reshape(b, c * r * r, h // r, w // r))
    b, h, w, c = x.shape
    return (x.reshape(b, h // r, r, w // r, r, c)
             .transpose(0, 1, 3, 2, 4, 5).reshape(b, h // r, w // r, c * r * r))


register_op("pixel_unshuffle", _pixel_unshuffle_raw)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return apply(_pixel_unshuffle_raw, (x,),
                 {"downscale_factor": int(downscale_factor),
                  "data_format": data_format}, name="pixel_unshuffle")


def _space_to_depth_raw(x, blocksize=1):
    """ref operators/space_to_depth_op.cc (NCHW; block-major channel order,
    which matches the reference kernel's (c*bs + offset) layout rather than
    pixel_unshuffle's channel-major)."""
    bs = blocksize
    b, c, h, w = x.shape
    return (x.reshape(b, c, h // bs, bs, w // bs, bs)
             .transpose(0, 3, 5, 1, 2, 4).reshape(b, c * bs * bs, h // bs, w // bs))


register_op("space_to_depth", _space_to_depth_raw)


def space_to_depth(x, blocksize, name=None):
    return apply(_space_to_depth_raw, (x,), {"blocksize": int(blocksize)},
                 name="space_to_depth")


# ------------------------------------------------- pooling with indices

def _max_pool2d_with_index_raw(x, kernel_size=(2, 2), stride=None,
                               padding=(0, 0)):
    """ref operators/max_pool2d_with_index_op (pool2d max + argmax over the
    flattened H*W map). Returns (out, flat_indices) — the indices feed
    max_unpool2d, exactly the reference pairing."""
    import jax
    import jax.numpy as jnp
    kh, kw = kernel_size
    sh, sw = (kh, kw) if stride is None else stride
    ph, pw = padding
    b, c, h, w = x.shape
    xf = x.reshape(b * c, 1, h, w)
    patches = jax.lax.conv_general_dilated_patches(
        xf, filter_shape=(kh, kw), window_strides=(sh, sw),
        padding=((ph, ph), (pw, pw)))                 # [BC, kh*kw, OH, OW]
    oh, ow = patches.shape[-2:]
    # the patch layout is deterministic: entry (d, i, j) reads source pixel
    # (i*sh - ph + d//kw, j*sw - pw + d%kw). Build the int32 index/validity
    # grids arithmetically — no float round-trip (flat indices above 2^24
    # would lose precision) and no extra convs.
    d = jnp.arange(kh * kw)
    rows = (jnp.arange(oh)[None, :, None] * sh - ph
            + (d // kw)[:, None, None])               # [kh*kw, OH, 1]
    cols = (jnp.arange(ow)[None, None, :] * sw - pw
            + (d % kw)[:, None, None])                # [kh*kw, 1, OW]
    valid = ((rows >= 0) & (rows < h) & (cols >= 0) & (cols < w))
    flat = (rows * w + cols).astype(jnp.int32)        # [kh*kw, OH, OW]
    neg = jnp.finfo(x.dtype).min
    vals = jnp.where(valid[None], patches, neg)
    arg = jnp.argmax(vals, axis=1)                    # [BC, OH, OW]
    out = jnp.max(vals, axis=1)
    idx = jnp.take_along_axis(
        jnp.broadcast_to(flat[None], (b * c,) + flat.shape),
        arg[:, None], axis=1)[:, 0]
    return (out.reshape(b, c, oh, ow),
            idx.reshape(b, c, oh, ow))


register_op("max_pool2d_with_index", _max_pool2d_with_index_raw)


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0, name=None):
    ks = (kernel_size,) * 2 if isinstance(kernel_size, int) else tuple(kernel_size)
    st = None if stride is None else (
        (stride,) * 2 if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * 2 if isinstance(padding, int) else tuple(padding)
    return apply(_max_pool2d_with_index_raw, (x,),
                 {"kernel_size": ks, "stride": st, "padding": pd},
                 name="max_pool2d_with_index")


def _max_unpool2d_raw(x, indices, output_hw=(1, 1)):
    """ref operators/unpool_op.cc: scatter pooled values back to the flat
    positions recorded by max_pool2d_with_index."""
    import jax.numpy as jnp
    b, c, oh, ow = x.shape
    H, W = output_hw
    src = x.reshape(b, c, oh * ow)
    idx = indices.reshape(b, c, oh * ow).astype(jnp.int32)
    bi = jnp.arange(b)[:, None, None]
    ci = jnp.arange(c)[None, :, None]
    # overlapping pool windows (stride < kernel) can record the SAME input
    # position from two output cells, making scatter-assign order-dependent;
    # scatter-max over a -inf init is deterministic. A scattered boolean
    # mask identifies untouched positions for the reference's zero fill
    # (comparing against the init value would misclassify legitimate
    # -inf / INT_MIN inputs).
    lo = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    flat = jnp.full((b, c, H * W), lo, x.dtype)
    flat = flat.at[bi, ci, idx].max(src)
    touched = jnp.zeros((b, c, H * W), jnp.bool_).at[bi, ci, idx].set(True)
    flat = jnp.where(touched, flat, jnp.zeros((), x.dtype))
    return flat.reshape(b, c, H, W)


register_op("max_unpool2d", _max_unpool2d_raw)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, name=None):
    from ..ops.dispatch import as_array as _aa
    xa = _aa(x)
    ks = (kernel_size,) * 2 if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * 2 if isinstance(stride, int) else tuple(stride))
    if output_size is None:
        oh, ow = xa.shape[-2:]
        output_size = ((oh - 1) * st[0] + ks[0], (ow - 1) * st[1] + ks[1])
    return apply(_max_unpool2d_raw, (x, indices),
                 {"output_hw": tuple(int(v) for v in output_size[-2:])},
                 name="max_unpool2d")


# ----------------------------------------------------------- yolov3 loss

def _yolov3_loss_raw(x, gt_box, gt_label, gt_score=None, anchors=(),
                     anchor_mask=(), class_num=1, ignore_thresh=0.7,
                     downsample_ratio=32, use_label_smooth=True):
    """YOLOv3 training loss (ref operators/detection/yolov3_loss_op.cc).

    x: [B, A*(5+C), H, W] raw head output for this scale (A = len(anchor_mask)),
    gt_box: [B, N, 4] normalised (cx, cy, w, h), gt_label: [B, N] int
    (rows with w<=0 are padding), gt_score: [B, N] optional per-gt mixup
    weight (scales that gt's loc/cls losses and is the objectness target,
    as in the reference's CalcObjnessLossGrad). Follows the reference split:
    sigmoid-CE on x/y, L1 on w/h (scaled by 2 - gw*gh), sigmoid-CE
    objectness with ignore zone (pred IoU vs any gt > ignore_thresh), and
    per-class sigmoid-CE at positive cells. The responsible anchor for a gt
    is the best whole-anchor-set wh-IoU match, positive only when that
    anchor belongs to this scale's mask — exactly the reference assignment.
    Returns [B] loss.
    """
    import jax
    import jax.numpy as jnp
    B, _, H, W = x.shape
    A = len(anchor_mask)
    C = class_num
    an = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)     # [An, 2] pixels
    mask = jnp.asarray(anchor_mask, jnp.int32)
    in_h, in_w = H * downsample_ratio, W * downsample_ratio

    p = x.reshape(B, A, 5 + C, H, W)
    tx, ty, tw, th = p[:, :, 0], p[:, :, 1], p[:, :, 2], p[:, :, 3]
    tobj, tcls = p[:, :, 4], p[:, :, 5:]                      # [B,A,H,W], [B,A,C,H,W]

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + jnp.log1p(
            jnp.exp(-jnp.abs(logit)))

    # ---- decoded pred boxes (normalised) for the ignore-zone IoU test
    gx = (jnp.arange(W)[None, None, None, :] + jax.nn.sigmoid(tx)) / W
    gy = (jnp.arange(H)[None, None, :, None] + jax.nn.sigmoid(ty)) / H
    aw = an[mask, 0][None, :, None, None]
    ah = an[mask, 1][None, :, None, None]
    pw = jnp.exp(tw) * aw / in_w
    phh = jnp.exp(th) * ah / in_h

    def iou_cwh(ax_, ay_, aw_, ah_, bx, by, bw, bh):
        x1 = jnp.maximum(ax_ - aw_ / 2, bx - bw / 2)
        x2 = jnp.minimum(ax_ + aw_ / 2, bx + bw / 2)
        y1 = jnp.maximum(ay_ - ah_ / 2, by - bh / 2)
        y2 = jnp.minimum(ay_ + ah_ / 2, by + bh / 2)
        inter = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
        return inter / jnp.maximum(aw_ * ah_ + bw * bh - inter, 1e-10)

    gb = gt_box.astype(jnp.float32)                           # [B, N, 4]
    gvalid = gb[:, :, 2] > 0                                  # [B, N]
    iou = iou_cwh(gx[..., None], gy[..., None], pw[..., None], phh[..., None],
                  gb[:, None, None, None, :, 0], gb[:, None, None, None, :, 1],
                  gb[:, None, None, None, :, 2], gb[:, None, None, None, :, 3])
    iou = jnp.where(gvalid[:, None, None, None, :], iou, 0.0)
    ignore = jnp.max(iou, axis=-1) > ignore_thresh            # [B,A,H,W]

    # ---- responsible-anchor assignment per gt (wh IoU over ALL anchors)
    gw_pix, gh_pix = gb[:, :, 2] * in_w, gb[:, :, 3] * in_h   # [B, N]
    inter = (jnp.minimum(gw_pix[:, :, None], an[None, None, :, 0]) *
             jnp.minimum(gh_pix[:, :, None], an[None, None, :, 1]))
    union = (gw_pix * gh_pix)[:, :, None] + (an[:, 0] * an[:, 1])[None, None] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=2)  # [B, N]
    # map best whole-set anchor -> slot in this scale's mask (or -1)
    slot = jnp.argmax(best[:, :, None] == mask[None, None, :], axis=2)
    in_mask = jnp.any(best[:, :, None] == mask[None, None, :], axis=2)
    resp = gvalid & in_mask                                   # [B, N]

    gi = jnp.clip((gb[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gb[:, :, 1] * H).astype(jnp.int32), 0, H - 1)
    # per-gt targets
    t_x = gb[:, :, 0] * W - gi
    t_y = gb[:, :, 1] * H - gj
    t_w = jnp.log(jnp.maximum(gw_pix / jnp.maximum(an[:, 0][best], 1e-10), 1e-10))
    t_h = jnp.log(jnp.maximum(gh_pix / jnp.maximum(an[:, 1][best], 1e-10), 1e-10))
    scale = 2.0 - gb[:, :, 2] * gb[:, :, 3]                   # [B, N]

    bi = jnp.broadcast_to(jnp.arange(B)[:, None], resp.shape)
    # gather predictions at each gt's assigned location
    px_g = tx[bi, slot, gj, gi]
    py_g = ty[bi, slot, gj, gi]
    pw_g = tw[bi, slot, gj, gi]
    ph_g = th[bi, slot, gj, gi]
    score = (jnp.ones_like(gb[:, :, 0]) if gt_score is None
             else gt_score.astype(jnp.float32))               # [B, N]
    loc = (bce(px_g, t_x) + bce(py_g, t_y)
           + jnp.abs(pw_g - t_w) + jnp.abs(ph_g - t_h)) * scale * score
    loc_loss = jnp.sum(jnp.where(resp, loc, 0.0), axis=1)     # [B]

    # ---- objectness: positives at responsible cells (target = gt_score),
    # negatives elsewhere. Scatter with .max: a padding/non-responsible row
    # writing 0 at a duplicate index must not clobber a positive.
    posw = jnp.zeros((B, A, H, W)).at[bi, slot, gj, gi].max(
        jnp.where(resp, score, 0.0), mode="drop")
    pos = posw > 0
    obj_pos = jnp.where(pos, bce(tobj, posw), 0.0)
    obj_neg = jnp.where((~pos) & (~ignore),
                        bce(tobj, jnp.zeros_like(tobj)), 0.0)
    obj_loss = jnp.sum(obj_pos + obj_neg, axis=(1, 2, 3))

    # ---- classification at positive cells (ref label_smooth: positives
    # 1 - sw, negatives sw, sw = min(1/C, 1/40))
    sw = min(1.0 / C, 1.0 / 40.0) if (use_label_smooth and C > 1) else 0.0
    onehot = jax.nn.one_hot(gt_label, C)                      # [B, N, C]
    tgt = onehot * (1.0 - sw) + (1.0 - onehot) * sw
    pcls_g = jnp.transpose(tcls, (0, 1, 3, 4, 2))[bi, slot, gj, gi]  # [B,N,C]
    cls = jnp.sum(bce(pcls_g, tgt), axis=2) * score
    cls_loss = jnp.sum(jnp.where(resp, cls, 0.0), axis=1)

    return loc_loss + obj_loss + cls_loss


register_op("yolov3_loss", _yolov3_loss_raw)


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    if gt_score is not None:
        return apply(_yolov3_loss_raw, (x, gt_box, gt_label, gt_score),
                     {"anchors": [int(a) for a in anchors],
                      "anchor_mask": [int(a) for a in anchor_mask],
                      "class_num": int(class_num),
                      "ignore_thresh": float(ignore_thresh),
                      "downsample_ratio": int(downsample_ratio),
                      "use_label_smooth": bool(use_label_smooth)},
                     name="yolov3_loss")
    return apply(_yolov3_loss_raw, (x, gt_box, gt_label),
                 {"anchors": [int(a) for a in anchors],
                  "anchor_mask": [int(a) for a in anchor_mask],
                  "class_num": int(class_num),
                  "ignore_thresh": float(ignore_thresh),
                  "downsample_ratio": int(downsample_ratio),
                  "use_label_smooth": bool(use_label_smooth)},
                 name="yolov3_loss")


# ------------------------------------------------------- precise roi pool

def _prroi_pool_raw(x, boxes, output_size=(1, 1), spatial_scale=1.0):
    """Precise ROI pooling (ref operators/prroi_pool_op.cc, PrRoIPool):
    each output bin is the exact integral of the bilinearly-interpolated
    feature surface over the bin, divided by bin area. The 1-D antiderivative
    of the triangle kernel gives a closed form per pixel, so the whole op is
    one [pixels x bins] weighted sum — fully differentiable w.r.t. both
    features AND box coordinates (the op's reason to exist).
    x: [1, C, H, W], boxes: [R, 4] -> [R, C, ph, pw]."""
    import jax
    import jax.numpy as jnp
    ph, pw = output_size
    img = x[0]
    c, h, w = img.shape

    def tri_int(t, p):
        """∫_{-inf}^{t} max(0, 1-|s-p|) ds, elementwise."""
        u = t - p
        left = 0.5 * jnp.square(jnp.clip(u + 1.0, 0.0, 1.0))
        right = 0.5 - 0.5 * jnp.square(jnp.clip(1.0 - u, 0.0, 1.0)) + 0.5
        return jnp.where(u <= 0, left, right)

    def seg_weight(a, b, p):
        """∫_a^b triangle(s - p) ds for every pixel coordinate p."""
        return tri_int(b, p) - tri_int(a, p)

    px = jnp.arange(w, dtype=jnp.float32)
    py = jnp.arange(h, dtype=jnp.float32)

    def one_roi(box):
        x1 = box[0] * spatial_scale
        y1 = box[1] * spatial_scale
        x2 = box[2] * spatial_scale
        y2 = box[3] * spatial_scale
        bw = jnp.maximum(x2 - x1, 1e-6) / pw
        bh = jnp.maximum(y2 - y1, 1e-6) / ph

        def one_bin(i, j):
            ax, bx_ = x1 + j * bw, x1 + (j + 1) * bw
            ay, by_ = y1 + i * bh, y1 + (i + 1) * bh
            wx = seg_weight(ax, bx_, px)            # [W]
            wy = seg_weight(ay, by_, py)            # [H]
            area = jnp.maximum((bx_ - ax) * (by_ - ay), 1e-6)
            return jnp.einsum("chw,h,w->c", img, wy, wx) / area

        ii, jj = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw), indexing="ij")
        bins = jax.vmap(jax.vmap(one_bin))(ii, jj)  # [ph, pw, C]
        return bins.transpose(2, 0, 1)

    return jax.vmap(one_roi)(boxes)


register_op("prroi_pool", _prroi_pool_raw)


def prroi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
               name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    from ..ops.dispatch import as_array as _aa
    if boxes_num is not None or _aa(x).shape[0] != 1:
        raise NotImplementedError("prroi_pool: pass one image per call")
    return apply(_prroi_pool_raw, (x, boxes),
                 {"output_size": tuple(output_size),
                  "spatial_scale": float(spatial_scale)}, name="prroi_pool")


# ----------------------------------------------------------- correlation

def _correlation_raw(x1, x2, max_displacement=1, stride2=1, pad_size=None):
    """Optical-flow correlation layer (ref operators/correlation_op.cc,
    FlowNet; kernel_size=1, stride1=1 — the shapes FlowNetC uses):
    out[b, k, i, j] = mean_c x1[b, c, i, j] * x2[b, c, i+dy, j+dx] over the
    displacement window dy,dx in [-d, d] step stride2; k indexes (dy, dx)
    row-major. Static unrolled shifts — XLA fuses them into one kernel."""
    import jax.numpy as jnp
    d = max_displacement
    if pad_size is None:
        pad_size = d
    b, c, h, w = x1.shape
    x2p = jnp.pad(x2, ((0, 0), (0, 0), (pad_size, pad_size),
                       (pad_size, pad_size)))
    outs = []
    for dy in range(-d, d + 1, stride2):
        for dx in range(-d, d + 1, stride2):
            sh = x2p[:, :, pad_size + dy:pad_size + dy + h,
                     pad_size + dx:pad_size + dx + w]
            outs.append(jnp.mean(x1 * sh, axis=1))
    return jnp.stack(outs, axis=1)


register_op("correlation", _correlation_raw)


def correlation(x1, x2, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply=1, name=None):
    if kernel_size != 1 or stride1 != 1:
        raise NotImplementedError(
            "correlation: kernel_size=1, stride1=1 supported (FlowNetC "
            "shapes); ref correlation_op.cc general case")
    if pad_size < max_displacement:
        raise ValueError(
            f"correlation: pad_size ({pad_size}) must be >= "
            f"max_displacement ({max_displacement}) or the displacement "
            f"window reads out of bounds")
    return apply(_correlation_raw, (x1, x2),
                 {"max_displacement": int(max_displacement),
                  "stride2": int(stride2), "pad_size": int(pad_size)},
                 name="correlation")


def _max_pool3d_with_index_raw(x, kernel_size=(2, 2, 2), stride=None,
                               padding=(0, 0, 0)):
    """ref operators/max_pool3d_with_index (NCDHW; flat D*H*W indices)."""
    import jax
    import jax.numpy as jnp
    kd, kh, kw = kernel_size
    sd, sh, sw = (kd, kh, kw) if stride is None else stride
    pd, ph, pw = padding
    b, c, D, h, w = x.shape
    xf = x.reshape(b * c, 1, D, h, w)
    patches = jax.lax.conv_general_dilated_patches(
        xf, filter_shape=(kd, kh, kw), window_strides=(sd, sh, sw),
        padding=((pd, pd), (ph, ph), (pw, pw)))   # [BC, kd*kh*kw, OD, OH, OW]
    od, oh, ow = patches.shape[-3:]
    dd = jnp.arange(kd * kh * kw)
    zz = (jnp.arange(od)[None, :, None, None] * sd - pd
          + (dd // (kh * kw))[:, None, None, None])
    yy = (jnp.arange(oh)[None, None, :, None] * sh - ph
          + ((dd // kw) % kh)[:, None, None, None])
    xx = (jnp.arange(ow)[None, None, None, :] * sw - pw
          + (dd % kw)[:, None, None, None])
    valid = ((zz >= 0) & (zz < D) & (yy >= 0) & (yy < h)
             & (xx >= 0) & (xx < w))
    flat = ((zz * h + yy) * w + xx).astype(jnp.int32)
    neg = jnp.finfo(x.dtype).min
    vals = jnp.where(valid[None], patches, neg)
    arg = jnp.argmax(vals, axis=1)
    out = jnp.max(vals, axis=1)
    idx = jnp.take_along_axis(
        jnp.broadcast_to(flat[None], (b * c,) + flat.shape),
        arg[:, None], axis=1)[:, 0]
    return (out.reshape(b, c, od, oh, ow), idx.reshape(b, c, od, oh, ow))


register_op("max_pool3d_with_index", _max_pool3d_with_index_raw)


# ------------------------------------------------ detection assembly tail

def _box_clip_raw(boxes, im_shape):
    """ref operators/detection/box_clip_op.cc: clamp corner boxes into
    [0, H-1] x [0, W-1]. boxes: [..., 4], im_shape: [2] (H, W)."""
    import jax.numpy as jnp
    h, w = im_shape[0], im_shape[1]
    x1 = jnp.clip(boxes[..., 0], 0, w - 1)
    y1 = jnp.clip(boxes[..., 1], 0, h - 1)
    x2 = jnp.clip(boxes[..., 2], 0, w - 1)
    y2 = jnp.clip(boxes[..., 3], 0, h - 1)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


register_op("box_clip", _box_clip_raw)


def box_clip(input, im_shape, name=None):
    return apply(_box_clip_raw, (input, im_shape), name="box_clip")


def _bipartite_match_raw(dist_mat, match_type="bipartite",
                         overlap_threshold=0.5):
    """Greedy bipartite matching (ref operators/detection/
    bipartite_match_op.cc): repeatedly take the globally largest entry,
    pairing its row (gt) to its column (prior); with
    match_type='per_prediction', unmatched columns whose best row overlap
    exceeds the threshold also match. Host numpy (sequential argmax over a
    small [N, M] matrix). Returns (col_to_row [M] int32, col_dist [M])."""
    import numpy as _np
    d = _np.asarray(dist_mat).copy()
    n, m = d.shape
    match = _np.full((m,), -1, _np.int32)
    mdist = _np.zeros((m,), _np.float32)
    live = d.copy()
    for _ in range(min(n, m)):
        idx = _np.unravel_index(_np.argmax(live), live.shape)
        if live[idx] <= 0:
            break
        r, c = idx
        match[c] = r
        mdist[c] = d[r, c]
        live[r, :] = -1.0
        live[:, c] = -1.0
    if match_type == "per_prediction":
        for c in range(m):
            if match[c] == -1:
                r = int(_np.argmax(d[:, c]))
                if d[r, c] >= overlap_threshold:
                    match[c] = r
                    mdist[c] = d[r, c]
    return jnp.asarray(match), jnp.asarray(mdist)


register_op("bipartite_match", _bipartite_match_raw)


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    return apply(_bipartite_match_raw, (dist_matrix,),
                 {"match_type": match_type,
                  "overlap_threshold": float(dist_threshold)},
                 differentiable=False, name="bipartite_match")


def _target_assign_raw(x, match_indices, fill_value=0.0):
    """ref operators/detection/target_assign_op.cc: out[i, j] =
    x[match[i, j]] rows gathered per batch, negatives filled.
    x: [N, K, D] (entity table per image), match_indices: [N, M]."""
    import jax.numpy as jnp
    idx = jnp.maximum(match_indices, 0)
    bi = jnp.arange(x.shape[0])[:, None]
    out = x[bi, idx]                                          # [N, M, D]
    neg = (match_indices < 0)[:, :, None]
    out = jnp.where(neg, jnp.asarray(fill_value, x.dtype), out)
    wt = jnp.where(match_indices < 0, 0.0, 1.0)[:, :, None]
    return out, wt


register_op("target_assign", _target_assign_raw)


def target_assign(input, matched_indices, mismatch_value=0.0, name=None):
    return apply(_target_assign_raw, (input, matched_indices),
                 {"fill_value": float(mismatch_value)},
                 differentiable=False, name="target_assign")


def _greedy_nms_host(boxes, order, thresh, shift=0.0, max_keep=None):
    """Vectorized host greedy NMS: one precomputed IoU matrix + O(len)
    rounds of boolean suppression (no nested python IoU loops)."""
    import numpy as _np
    if order.size == 0:
        return []
    b = boxes[order]
    area = (b[:, 2] - b[:, 0] + shift) * (b[:, 3] - b[:, 1] + shift)
    x1 = _np.maximum(b[:, None, 0], b[None, :, 0])
    y1 = _np.maximum(b[:, None, 1], b[None, :, 1])
    x2 = _np.minimum(b[:, None, 2], b[None, :, 2])
    y2 = _np.minimum(b[:, None, 3], b[None, :, 3])
    inter = _np.maximum(x2 - x1 + shift, 0) * _np.maximum(y2 - y1 + shift, 0)
    iou = inter / _np.maximum(area[:, None] + area[None, :] - inter, 1e-10)
    live = _np.ones(order.size, bool)
    kept = []
    for i in range(order.size):
        if not live[i]:
            continue
        kept.append(order[i])
        if max_keep is not None and len(kept) >= max_keep:
            break
        live &= iou[i] <= thresh
        live[i] = False
    return kept


def _nms_host_single(bx, sc, score_threshold, nms_top_k, keep_top_k,
                     nms_threshold, background_label, shift):
    import numpy as _np
    C, M = sc.shape
    cand = []
    for c in range(C):
        if c == background_label:
            continue
        keep = _np.where(sc[c] > score_threshold)[0]
        if keep.size == 0:
            continue
        order = keep[_np.argsort(-sc[c][keep])][:nms_top_k]
        for k in _greedy_nms_host(bx, order, nms_threshold, shift):
            cand.append((c, float(sc[c][k]), bx[k]))
    cand.sort(key=lambda t: -t[1])
    cand = cand[:keep_top_k]
    out = _np.full((keep_top_k, 6), -1.0, _np.float32)
    for i, (c, s, b) in enumerate(cand):
        out[i] = [c, s, b[0], b[1], b[2], b[3]]
    return out, _np.int32(len(cand))


def _multiclass_nms_raw(bboxes, scores, score_threshold=0.05, nms_top_k=64,
                        keep_top_k=16, nms_threshold=0.3, background_label=0,
                        normalized=True):
    """Per-class NMS + cross-class top-k (ref operators/detection/
    multiclass_nms_op.cc). bboxes: [M, 4], scores: [C, M] — or the
    batched reference layout [N, M, 4] / [N, C, M]. The reference emits
    a LoD list; the dense form is a fixed [(N,) keep_top_k, 6] tensor of
    (label, score, x1, y1, x2, y2) rows padded with label=-1, plus the
    valid count(s). Inherently sequential greedy suppression runs on the
    HOST; under tracing (the jitted Executor / translated reference
    programs) it enters the program as a pure_callback with the static
    [keep_top_k, 6] result shape."""
    import numpy as _np
    shift = 0.0 if normalized else 1.0
    batched = getattr(bboxes, "ndim", 2) == 3

    def host(bx, sc):
        bx, sc = _np.asarray(bx), _np.asarray(sc)
        if batched:
            outs, counts = zip(*[
                _nms_host_single(b, s, score_threshold, nms_top_k,
                                 keep_top_k, nms_threshold,
                                 background_label, shift)
                for b, s in zip(bx, sc)])
            return _np.stack(outs), _np.asarray(counts, _np.int32)
        return _nms_host_single(bx, sc, score_threshold, nms_top_k,
                                keep_top_k, nms_threshold,
                                background_label, shift)

    if isinstance(bboxes, jax.core.Tracer) \
            or isinstance(scores, jax.core.Tracer):
        if batched:
            n = bboxes.shape[0]
            shapes = (jax.ShapeDtypeStruct((n, keep_top_k, 6), jnp.float32),
                      jax.ShapeDtypeStruct((n,), jnp.int32))
        else:
            shapes = (jax.ShapeDtypeStruct((keep_top_k, 6), jnp.float32),
                      jax.ShapeDtypeStruct((), jnp.int32))
        return jax.pure_callback(host, shapes, bboxes, scores)
    out, count = host(bboxes, scores)
    return jnp.asarray(out), jnp.asarray(count)


register_op("multiclass_nms", _multiclass_nms_raw)


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=64,
                   keep_top_k=16, nms_threshold=0.3, normalized=True,
                   background_label=0, name=None):
    return apply(_multiclass_nms_raw, (bboxes, scores),
                 {"score_threshold": float(score_threshold),
                  "nms_top_k": int(nms_top_k),
                  "keep_top_k": int(keep_top_k),
                  "nms_threshold": float(nms_threshold),
                  "background_label": int(background_label),
                  "normalized": bool(normalized)},
                 differentiable=False, name="multiclass_nms")


def _generate_proposals_raw(scores, bbox_deltas, im_shape, anchors,
                            variances, pre_nms_top_n=128, post_nms_top_n=32,
                            nms_thresh=0.5, min_size=0.1):
    """RPN proposal generation (ref operators/detection/
    generate_proposals_op.cc): decode anchor deltas, clip to image, drop
    tiny boxes, pre-NMS top-N by score, greedy NMS, post-NMS top-N.
    Single image: scores [A], bbox_deltas [A, 4], anchors [A, 4],
    variances [A, 4]. Dense output: ([post_nms_top_n, 4] padded rois,
    count)."""
    import numpy as _np
    sc = _np.asarray(scores).reshape(-1)
    dl = _np.asarray(bbox_deltas).reshape(-1, 4)
    an = _np.asarray(anchors).reshape(-1, 4)
    vr = _np.asarray(variances).reshape(-1, 4)
    h, w = float(_np.asarray(im_shape)[0]), float(_np.asarray(im_shape)[1])
    # decode (center-size, like box_coder decode)
    aw = an[:, 2] - an[:, 0] + 1.0
    ah = an[:, 3] - an[:, 1] + 1.0
    ax = an[:, 0] + aw * 0.5
    ay = an[:, 1] + ah * 0.5
    cx = vr[:, 0] * dl[:, 0] * aw + ax
    cy = vr[:, 1] * dl[:, 1] * ah + ay
    bw = _np.exp(_np.minimum(vr[:, 2] * dl[:, 2], 10.0)) * aw
    bh = _np.exp(_np.minimum(vr[:, 3] * dl[:, 3], 10.0)) * ah
    boxes = _np.stack([cx - bw / 2, cy - bh / 2,
                       cx + bw / 2 - 1, cy + bh / 2 - 1], axis=1)
    boxes[:, 0::2] = _np.clip(boxes[:, 0::2], 0, w - 1)
    boxes[:, 1::2] = _np.clip(boxes[:, 1::2], 0, h - 1)
    ws = boxes[:, 2] - boxes[:, 0] + 1
    hs = boxes[:, 3] - boxes[:, 1] + 1
    keep = _np.where((ws >= min_size) & (hs >= min_size))[0]
    order = keep[_np.argsort(-sc[keep])][:pre_nms_top_n]
    kept = _greedy_nms_host(boxes, order, nms_thresh, shift=1.0,
                            max_keep=post_nms_top_n)
    out = _np.zeros((post_nms_top_n, 4), _np.float32)
    for i, k in enumerate(kept):
        out[i] = boxes[k]
    return jnp.asarray(out), jnp.int32(len(kept))


register_op("generate_proposals", _generate_proposals_raw)


def generate_proposals(scores, bbox_deltas, im_shape, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, name=None):
    return apply(_generate_proposals_raw,
                 (scores, bbox_deltas, im_shape, anchors, variances),
                 {"pre_nms_top_n": int(pre_nms_top_n),
                  "post_nms_top_n": int(post_nms_top_n),
                  "nms_thresh": float(nms_thresh),
                  "min_size": float(min_size)},
                 differentiable=False, name="generate_proposals")


def _distribute_fpn_proposals_raw(rois, min_level=2, max_level=5,
                                  refer_level=4, refer_scale=224):
    """ref operators/detection/distribute_fpn_proposals_op.cc: assign each
    roi to level floor(refer_level + log2(sqrt(area)/refer_scale)),
    clamped. Dense output: (level_ids [N] int32, restore_index [N]) — the
    per-level splits are boolean masks over level_ids, static-shape
    friendly."""
    import jax.numpy as jnp
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = jnp.sqrt(jnp.maximum(w * h, 1e-10))
    lvl = jnp.floor(refer_level + jnp.log2(scale / refer_scale + 1e-10))
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    order = jnp.argsort(lvl, stable=True)
    restore = jnp.argsort(order, stable=True).astype(jnp.int32)
    return lvl, restore


register_op("distribute_fpn_proposals", _distribute_fpn_proposals_raw)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    return apply(_distribute_fpn_proposals_raw, (fpn_rois,),
                 {"min_level": int(min_level), "max_level": int(max_level),
                  "refer_level": int(refer_level),
                  "refer_scale": int(refer_scale)},
                 differentiable=False, name="distribute_fpn_proposals")


def _polygon_box_transform_raw(x):
    """ref operators/detection/polygon_box_transform_op.cc (EAST OCR):
    input [B, 8, H, W] of per-pixel quad offsets; output absolute quad
    coordinates: out[:, 2k] = 4*j - x[:, 2k], out[:, 2k+1] = 4*i - x."""
    import jax.numpy as jnp
    b, c, h, w = x.shape
    jj = jnp.arange(w)[None, None, None, :] * 4.0
    ii = jnp.arange(h)[None, None, :, None] * 4.0
    even = jj - x[:, 0::2]
    odd = ii - x[:, 1::2]
    out = jnp.zeros_like(x)
    out = out.at[:, 0::2].set(even)
    out = out.at[:, 1::2].set(odd)
    return out


register_op("polygon_box_transform", _polygon_box_transform_raw)


def polygon_box_transform(input, name=None):
    return apply(_polygon_box_transform_raw, (input,),
                 differentiable=False, name="polygon_box_transform")


def _collect_fpn_proposals_raw(*args, post_nms_top_n=16):
    """ref operators/detection/collect_fpn_proposals_op.cc: concat
    per-level (rois, scores) pairs and keep the global top-N by score.
    args = L roi tensors [Ni, 4] then L score tensors [Ni]."""
    import jax.numpy as jnp
    L = len(args) // 2
    rois = jnp.concatenate(args[:L], axis=0)
    scores = jnp.concatenate(args[L:], axis=0)
    k = min(post_nms_top_n, scores.shape[0])
    top_s, idx = jax.lax.top_k(scores, k)
    out = jnp.zeros((post_nms_top_n, 4), rois.dtype)
    out = out.at[:k].set(rois[idx])
    return out, jnp.int32(k)


register_op("collect_fpn_proposals", _collect_fpn_proposals_raw)


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    return apply(_collect_fpn_proposals_raw,
                 tuple(multi_rois) + tuple(multi_scores),
                 {"post_nms_top_n": int(post_nms_top_n)},
                 differentiable=False, name="collect_fpn_proposals")


def _box_decoder_and_assign_raw(prior_box, prior_box_var, target_box,
                                box_score, box_clip=4.135):
    """ref operators/detection/box_decoder_and_assign_op.cc: decode
    per-class box deltas against priors, then assign each roi its
    best-scoring non-background class's box.
    prior_box [N,4], target_box [N, C*4], box_score [N, C]."""
    import jax.numpy as jnp
    N, C = box_score.shape
    pw = prior_box[:, 2] - prior_box[:, 0] + 1.0
    ph = prior_box[:, 3] - prior_box[:, 1] + 1.0
    px = prior_box[:, 0] + pw * 0.5
    py = prior_box[:, 1] + ph * 0.5
    d = target_box.reshape(N, C, 4) * prior_box_var.reshape(
        1, 1, 4) if prior_box_var.ndim == 1 else \
        target_box.reshape(N, C, 4) * prior_box_var[:, None, :]
    cx = d[:, :, 0] * pw[:, None] + px[:, None]
    cy = d[:, :, 1] * ph[:, None] + py[:, None]
    bw = jnp.exp(jnp.minimum(d[:, :, 2], box_clip)) * pw[:, None]
    bh = jnp.exp(jnp.minimum(d[:, :, 3], box_clip)) * ph[:, None]
    decoded = jnp.stack([cx - bw / 2, cy - bh / 2,
                         cx + bw / 2 - 1, cy + bh / 2 - 1],
                        axis=2)                     # [N, C, 4]
    best = jnp.argmax(box_score[:, 1:], axis=1) + 1  # skip background 0
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, 2), axis=1)[:, 0]
    return decoded.reshape(N, C * 4), assigned


register_op("box_decoder_and_assign", _box_decoder_and_assign_raw)


def _mine_hard_examples_raw(cls_loss, match_indices, neg_pos_ratio=3.0,
                            mining_type="max_negative"):
    """OHEM negative mining (ref operators/detection/
    mine_hard_examples_op.cc, max_negative mode): per row, keep the
    neg_pos_ratio * num_pos highest-loss negatives. Returns a [B, M]
    int32 mask (1 = selected negative)."""
    import jax.numpy as jnp
    if mining_type != "max_negative":
        raise NotImplementedError(
            "mine_hard_examples: only max_negative mining implemented "
            "(ref hard_example mode keeps a global sample_size)")
    neg = match_indices < 0                              # [B, M]
    n_pos = jnp.sum(~neg, axis=1)                        # [B]
    n_keep = jnp.minimum((n_pos * neg_pos_ratio).astype(jnp.int32),
                         jnp.sum(neg, axis=1))
    loss_neg = jnp.where(neg, cls_loss, -jnp.inf)
    order = jnp.argsort(-loss_neg, axis=1)
    rank = jnp.argsort(order, axis=1)                    # rank of each col
    return (rank < n_keep[:, None]).astype(jnp.int32)


register_op("mine_hard_examples", _mine_hard_examples_raw)


def mine_hard_examples(cls_loss, match_indices, neg_pos_ratio=3.0,
                       name=None):
    return apply(_mine_hard_examples_raw, (cls_loss, match_indices),
                 {"neg_pos_ratio": float(neg_pos_ratio)},
                 differentiable=False, name="mine_hard_examples")


def _tdm_child_raw(x, tree_info, child_nums=2):
    """ref operators/tdm_child_op.cc: look up each node id's children in
    the TDM tree table. tree_info: [total_nodes, 3 + child_nums] rows of
    (item_id, layer_id, parent_id, child_ids...). Returns (child ids
    [..., child_nums], leaf mask)."""
    import jax.numpy as jnp
    ids = x.astype(jnp.int32)
    children = tree_info[ids][..., 3:3 + child_nums].astype(jnp.int32)
    item = tree_info[children][..., 0]
    leaf_mask = ((children != 0) & (item != 0)).astype(jnp.int32)
    return children, leaf_mask


register_op("tdm_child", _tdm_child_raw)


# ------------------------------------------------- training target assign

def _iou_corner_np(a, b):
    import numpy as _np
    area_a = _np.maximum(a[:, 2] - a[:, 0], 0) * _np.maximum(
        a[:, 3] - a[:, 1], 0)
    area_b = _np.maximum(b[:, 2] - b[:, 0], 0) * _np.maximum(
        b[:, 3] - b[:, 1], 0)
    x1 = _np.maximum(a[:, None, 0], b[None, :, 0])
    y1 = _np.maximum(a[:, None, 1], b[None, :, 1])
    x2 = _np.minimum(a[:, None, 2], b[None, :, 2])
    y2 = _np.minimum(a[:, None, 3], b[None, :, 3])
    inter = _np.maximum(x2 - x1, 0) * _np.maximum(y2 - y1, 0)
    return inter / _np.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def _encode_center_np(anchors, gts):
    """box_coder encode_center_size, numpy (targets for matched pairs)."""
    import numpy as _np
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + aw * 0.5
    ay = anchors[:, 1] + ah * 0.5
    gw = gts[:, 2] - gts[:, 0] + 1.0
    gh = gts[:, 3] - gts[:, 1] + 1.0
    gx = gts[:, 0] + gw * 0.5
    gy = gts[:, 1] + gh * 0.5
    return _np.stack([(gx - ax) / aw, (gy - ay) / ah,
                      _np.log(_np.maximum(gw / aw, 1e-10)),
                      _np.log(_np.maximum(gh / ah, 1e-10))], axis=1)


def _rpn_target_assign_raw(anchors, gt_boxes, rpn_batch_size_per_im=256,
                           rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                           rpn_negative_overlap=0.3, seed=0):
    """RPN anchor sampling (ref operators/detection/rpn_target_assign_op.cc):
    positives = best anchor per gt + anchors with IoU > positive_overlap;
    negatives = IoU < negative_overlap; seeded random subsample to the
    fg-fraction budget. Dense outputs: labels [A] int32 (1 pos / 0 neg /
    -1 ignore) and bbox targets [A, 4] (zero rows for non-positives)."""
    import numpy as _np
    an = _np.asarray(anchors)
    gt = _np.asarray(gt_boxes)
    A = an.shape[0]
    rng = _np.random.RandomState(seed)
    labels = _np.full((A,), -1, _np.int32)
    tgt = _np.zeros((A, 4), _np.float32)
    if gt.shape[0]:
        iou = _iou_corner_np(an, gt)                 # [A, G]
        best_gt = iou.argmax(axis=1)
        best_iou = iou.max(axis=1)
        labels[best_iou < rpn_negative_overlap] = 0
        labels[iou.argmax(axis=0)] = 1               # best anchor per gt
        labels[best_iou >= rpn_positive_overlap] = 1
        n_fg = int(rpn_batch_size_per_im * rpn_fg_fraction)
        fg = _np.where(labels == 1)[0]
        if fg.size > n_fg:
            labels[rng.choice(fg, fg.size - n_fg, replace=False)] = -1
        n_bg = rpn_batch_size_per_im - min(fg.size, n_fg)
        bg = _np.where(labels == 0)[0]
        if bg.size > n_bg:
            labels[rng.choice(bg, bg.size - n_bg, replace=False)] = -1
        pos = _np.where(labels == 1)[0]
        tgt[pos] = _encode_center_np(an[pos], gt[best_gt[pos]])
    else:
        labels[:] = 0
    return jnp.asarray(labels), jnp.asarray(tgt)


register_op("rpn_target_assign", _rpn_target_assign_raw)


def _retinanet_target_assign_raw(anchors, gt_boxes, positive_overlap=0.5,
                                 negative_overlap=0.4):
    """RetinaNet assignment (ref operators/detection/retinanet_target_
    assign_op.cc): like RPN but NO subsampling (focal loss consumes all
    anchors). Returns (labels [A] with gt class slot 1 for matched —
    callers combine with gt labels —, bbox targets [A, 4])."""
    import numpy as _np
    an = _np.asarray(anchors)
    gt = _np.asarray(gt_boxes)
    A = an.shape[0]
    labels = _np.full((A,), -1, _np.int32)
    tgt = _np.zeros((A, 4), _np.float32)
    if gt.shape[0]:
        iou = _iou_corner_np(an, gt)
        best_gt = iou.argmax(axis=1)
        best_iou = iou.max(axis=1)
        labels[best_iou < negative_overlap] = 0
        labels[best_iou >= positive_overlap] = 1
        labels[iou.argmax(axis=0)] = 1
        pos = _np.where(labels == 1)[0]
        tgt[pos] = _encode_center_np(an[pos], gt[best_gt[pos]])
    else:
        labels[:] = 0
    return jnp.asarray(labels), jnp.asarray(tgt)


register_op("retinanet_target_assign", _retinanet_target_assign_raw)


def _generate_proposal_labels_raw(rois, gt_boxes, gt_classes,
                                  batch_size_per_im=64, fg_fraction=0.25,
                                  fg_thresh=0.5, bg_thresh_hi=0.5,
                                  bg_thresh_lo=0.0, seed=0):
    """Second-stage RoI sampling (ref operators/detection/
    generate_proposal_labels_op.cc): label rois by IoU against gt, seeded
    fg/bg subsample, regression targets for foregrounds. Dense outputs:
    (sampled rois [S, 4], labels [S] int32 (-1 pad), bbox targets [S, 4])
    with S = batch_size_per_im."""
    import numpy as _np
    r = _np.asarray(rois)
    gt = _np.asarray(gt_boxes)
    gc = _np.asarray(gt_classes).reshape(-1)
    rng = _np.random.RandomState(seed)
    S = batch_size_per_im
    all_rois = _np.concatenate([r, gt], axis=0) if gt.size else r
    iou = _iou_corner_np(all_rois, gt) if gt.size else _np.zeros(
        (all_rois.shape[0], 0))
    best = iou.max(axis=1) if gt.size else _np.zeros(all_rois.shape[0])
    best_gt = iou.argmax(axis=1) if gt.size else _np.zeros(
        all_rois.shape[0], _np.int64)
    fg = _np.where(best >= fg_thresh)[0]
    bg = _np.where((best < bg_thresh_hi) & (best >= bg_thresh_lo))[0]
    n_fg = min(int(S * fg_fraction), fg.size)
    n_bg = min(S - n_fg, bg.size)
    fg = rng.choice(fg, n_fg, replace=False) if fg.size > n_fg else fg
    bg = rng.choice(bg, n_bg, replace=False) if bg.size > n_bg else bg
    keep = _np.concatenate([fg, bg]).astype(_np.int64)
    out_rois = _np.zeros((S, 4), _np.float32)
    out_lab = _np.full((S,), -1, _np.int32)
    out_tgt = _np.zeros((S, 4), _np.float32)
    k = keep.size
    out_rois[:k] = all_rois[keep]
    out_lab[:len(fg)] = gc[best_gt[fg]] if gt.size else 0
    out_lab[len(fg):k] = 0
    if gt.size and len(fg):
        out_tgt[:len(fg)] = _encode_center_np(all_rois[fg], gt[best_gt[fg]])
    return jnp.asarray(out_rois), jnp.asarray(out_lab), jnp.asarray(out_tgt)


register_op("generate_proposal_labels", _generate_proposal_labels_raw)


def _detection_map_raw(detections, det_count, gt_boxes, gt_labels,
                       overlap_threshold=0.5, class_num=2,
                       ap_type="integral"):
    """VOC-style mAP (ref operators/detection/detection_map_op.cc) for one
    image batch in the dense contract: detections [D, 6] rows of (label,
    score, x1, y1, x2, y2) with det_count valid, gt_boxes [G, 4],
    gt_labels [G] (-1 pads). Host numpy; returns scalar mAP."""
    import numpy as _np
    det = _np.asarray(detections)[:int(det_count)]
    gtb = _np.asarray(gt_boxes)
    gtl = _np.asarray(gt_labels).reshape(-1)
    valid = gtl >= 0
    gtb, gtl = gtb[valid], gtl[valid]
    aps = []
    for c in range(class_num):
        gt_c = gtb[gtl == c]
        det_c = det[det[:, 0] == c]
        if gt_c.shape[0] == 0:
            continue
        order = _np.argsort(-det_c[:, 1])
        det_c = det_c[order]
        used = _np.zeros(gt_c.shape[0], bool)
        tp = _np.zeros(det_c.shape[0])
        fp = _np.zeros(det_c.shape[0])
        iou_all = _iou_corner_np(det_c[:, 2:6], gt_c) if det_c.size else \
            _np.zeros((0, gt_c.shape[0]))
        for i in range(det_c.shape[0]):
            j = iou_all[i].argmax() if gt_c.shape[0] else 0
            if gt_c.shape[0] and iou_all[i, j] >= overlap_threshold \
                    and not used[j]:
                tp[i] = 1
                used[j] = True
            else:
                fp[i] = 1
        ctp = _np.cumsum(tp)
        cfp = _np.cumsum(fp)
        rec = ctp / gt_c.shape[0]
        prec = ctp / _np.maximum(ctp + cfp, 1e-10)
        if ap_type == "11point":
            ap = _np.mean([prec[rec >= t].max() if (rec >= t).any() else 0.0
                           for t in _np.linspace(0, 1, 11)])
        else:  # integral
            ap = 0.0
            mrec = _np.concatenate([[0.0], rec, [1.0]])
            mpre = _np.concatenate([[0.0], prec, [0.0]])
            for i in range(mpre.size - 2, -1, -1):
                mpre[i] = max(mpre[i], mpre[i + 1])
            idx = _np.where(mrec[1:] != mrec[:-1])[0]
            ap = _np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1])
        aps.append(ap)
    return jnp.float32(_np.mean(aps) if aps else 0.0)


register_op("detection_map", _detection_map_raw)


def detection_map(detect_res, det_count, gt_boxes, gt_labels,
                  class_num, overlap_threshold=0.5, ap_type="integral",
                  name=None):
    return apply(_detection_map_raw,
                 (detect_res, det_count, gt_boxes, gt_labels),
                 {"overlap_threshold": float(overlap_threshold),
                  "class_num": int(class_num), "ap_type": str(ap_type)},
                 differentiable=False, name="detection_map")


def _deformable_psroi_pooling_raw(x, boxes, trans, output_size=(3, 3),
                                  spatial_scale=1.0, trans_std=0.1,
                                  sample_per_part=2):
    """Deformable position-sensitive RoI pooling (ref operators/
    deformable_psroi_pooling_op.cc, Deformable R-FCN): each bin's sample
    grid is shifted by a learned offset (trans [R, 2, ph, pw], scaled by
    trans_std and roi size), values bilinearly sampled from the bin's
    position-sensitive channel group and averaged.
    x: [1, C, H, W] with C = oc*ph*pw, boxes: [R, 4] -> [R, oc, ph, pw].
    Differentiable w.r.t. x, boxes AND trans (the point of the op)."""
    import jax
    import jax.numpy as jnp
    ph, pw = output_size
    img = x[0]
    c, h, w = img.shape
    oc = c // (ph * pw)
    s = sample_per_part

    def bilinear(plane, yy, xx):
        y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        wy = jnp.clip(yy - y0, 0.0, 1.0)
        wx = jnp.clip(xx - x0, 0.0, 1.0)
        y0i, x0i = y0.astype(int), x0.astype(int)
        y1i, x1i = y1.astype(int), x1.astype(int)
        return (plane[y0i, x0i] * (1 - wy) * (1 - wx)
                + plane[y0i, x1i] * (1 - wy) * wx
                + plane[y1i, x0i] * wy * (1 - wx)
                + plane[y1i, x1i] * wy * wx)

    def one_roi(box, tr):
        x1 = box[0] * spatial_scale - 0.5
        y1 = box[1] * spatial_scale - 0.5
        x2 = (box[2] + 1.0) * spatial_scale - 0.5
        y2 = (box[3] + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw, bh = rw / pw, rh / ph

        def one_bin(k, i, j):
            dx = tr[0, i, j] * trans_std * rw
            dy = tr[1, i, j] * trans_std * rh
            gy = (y1 + i * bh + dy
                  + (jnp.arange(s) + 0.5) / s * bh)[:, None]
            gx = (x1 + j * bw + dx
                  + (jnp.arange(s) + 0.5) / s * bw)[None, :]
            ch = (k * ph + i) * pw + j
            vals = bilinear(img[ch], jnp.broadcast_to(gy, (s, s)),
                            jnp.broadcast_to(gx, (s, s)))
            return jnp.mean(vals)

        kk, ii, jj = jnp.meshgrid(jnp.arange(oc), jnp.arange(ph),
                                  jnp.arange(pw), indexing="ij")
        return jax.vmap(jax.vmap(jax.vmap(one_bin)))(kk, ii, jj)

    return jax.vmap(one_roi)(boxes, trans)


register_op("deformable_psroi_pooling", _deformable_psroi_pooling_raw)


def _roi_perspective_transform_raw(x, rois, transformed_height=4,
                                   transformed_width=4, spatial_scale=1.0):
    """Perspective-warp RoI quads to a fixed rectangle (ref operators/
    detection/roi_perspective_transform_op.cc, OCR text-line
    rectification). rois: [R, 8] quad corners (x1 y1 ... x4 y4 in
    clockwise order); each output pixel samples the input bilinearly
    through the quad->rect homography. x: [1, C, H, W]."""
    import jax
    import jax.numpy as jnp
    img = x[0]
    c, h, w = img.shape
    TH, TW = transformed_height, transformed_width

    def one_roi(quad):
        q = quad.reshape(4, 2) * spatial_scale
        # homography rect(u,v in [0,W-1]x[0,H-1]) -> quad: solve 8x8
        src = jnp.asarray([[0.0, 0.0], [TW - 1.0, 0.0],
                           [TW - 1.0, TH - 1.0], [0.0, TH - 1.0]])
        rows = []
        rhs = []
        for k in range(4):
            u, v = src[k, 0], src[k, 1]
            X, Y = q[k, 0], q[k, 1]
            rows.append(jnp.stack(
                [u, v, jnp.asarray(1.0), jnp.asarray(0.0),
                 jnp.asarray(0.0), jnp.asarray(0.0), -u * X, -v * X]))
            rhs.append(X)
            rows.append(jnp.stack(
                [jnp.asarray(0.0), jnp.asarray(0.0), jnp.asarray(0.0),
                 u, v, jnp.asarray(1.0), -u * Y, -v * Y]))
            rhs.append(Y)
        A = jnp.stack(rows)
        b = jnp.stack(rhs)
        # degenerate quads (zero/collinear rows — e.g. the dense contract's
        # zero-padded rois) make A singular; NaN from the solve would
        # poison the whole vmapped batch's gradients, so regularise and
        # zero the output instead
        degenerate = jnp.abs(jnp.linalg.det(A)) < 1e-6
        A = jnp.where(degenerate, A + jnp.eye(8), A)
        hvec = jnp.linalg.solve(A, b)
        H3 = jnp.concatenate([hvec, jnp.ones((1,))]).reshape(3, 3)
        uu, vv = jnp.meshgrid(jnp.arange(TW, dtype=jnp.float32),
                              jnp.arange(TH, dtype=jnp.float32))
        ones = jnp.ones_like(uu)
        pts = jnp.stack([uu, vv, ones], axis=0).reshape(3, -1)
        mapped = H3 @ pts
        xs = mapped[0] / jnp.maximum(jnp.abs(mapped[2]), 1e-8) * \
            jnp.sign(mapped[2])
        ys = mapped[1] / jnp.maximum(jnp.abs(mapped[2]), 1e-8) * \
            jnp.sign(mapped[2])

        x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
        y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
        x1_ = jnp.clip(x0 + 1, 0, w - 1)
        y1_ = jnp.clip(y0 + 1, 0, h - 1)
        wx = jnp.clip(xs - x0, 0.0, 1.0)
        wy = jnp.clip(ys - y0, 0.0, 1.0)
        x0i, y0i = x0.astype(int), y0.astype(int)
        x1i, y1i = x1_.astype(int), y1_.astype(int)
        vals = (img[:, y0i, x0i] * (1 - wy) * (1 - wx)
                + img[:, y0i, x1i] * (1 - wy) * wx
                + img[:, y1i, x0i] * wy * (1 - wx)
                + img[:, y1i, x1i] * wy * wx)
        inside = ((xs >= -1) & (xs <= w) & (ys >= -1) & (ys <= h))
        vals = jnp.where(inside[None, :] & ~degenerate, vals, 0.0)
        return vals.reshape(c, TH, TW)

    return jax.vmap(one_roi)(rois)


register_op("roi_perspective_transform", _roi_perspective_transform_raw)


def _tdm_sampler_raw(leaf_ids, travel_list, layer_list, neg_samples_list=(),
                     seed=0, output_positive=True):
    """TDM layer-wise sampling (ref operators/tdm_sampler_op.cc): for each
    positive leaf, emit its ancestor per tree layer (travel_list row) plus
    `neg_samples_list[l]` seeded negatives drawn from that layer's node
    set (layer_list row, 0-padded). Host numpy. Returns (out ids
    [B, sum(1+neg_l)], labels same shape)."""
    import numpy as _np
    ids = _np.asarray(leaf_ids).reshape(-1)
    travel = _np.asarray(travel_list)          # [num_leaves, L]
    layers = _np.asarray(layer_list)           # [L, max_layer_nodes]
    L = travel.shape[1]
    neg = list(neg_samples_list) or [1] * L
    if len(neg) != L:
        raise ValueError(
            f"tdm_sampler: neg_samples_list has {len(neg)} entries but the "
            f"travel table has {L} layers (ref requires equal length)")
    rng = _np.random.RandomState(seed)
    width = sum((1 if output_positive else 0) + n for n in neg)
    out = _np.zeros((ids.size, width), _np.int32)
    lab = _np.zeros((ids.size, width), _np.int32)
    for b, leaf in enumerate(ids):
        k = 0
        for l in range(L):
            pos = travel[leaf, l]
            if pos == 0:        # 0-padded layer (unbalanced tree): skip,
                k += (1 if output_positive else 0) + neg[l]   # keep label 0
                continue
            if output_positive:
                out[b, k] = pos
                lab[b, k] = 1
                k += 1
            nodes = layers[l][layers[l] > 0]
            nodes = nodes[nodes != pos]
            n = min(neg[l], nodes.size)
            if n:
                out[b, k:k + n] = rng.choice(nodes, n, replace=False)
            k += neg[l]
    return jnp.asarray(out), jnp.asarray(lab)


register_op("tdm_sampler", _tdm_sampler_raw)


def _similarity_focus_raw(x, axis=1, indexes=(0,)):
    """ref operators/similarity_focus_op.h: for each selected index along
    `axis`, greedily pick cells of the remaining 2D plane in descending
    value order with no repeated row/col (an assignment-style focus), and
    set those positions to 1 across the whole `axis` dimension. Host numpy
    (sorting-based mask synthesis, non-differentiable)."""
    import numpy as _np
    if axis not in (1, 2, 3):
        raise ValueError(
            f"similarity_focus: axis must be 1, 2 or 3 (got {axis}) — "
            "ref similarity_focus_op.h enforces the same")
    a = _np.asarray(x)
    B, d1, d2, d3 = a.shape
    out = _np.zeros_like(a)
    for b in range(B):
        for index in indexes:
            if axis == 1:
                plane = a[b, index]                     # [d2, d3]
                n1, n2 = d2, d3
            elif axis == 2:
                plane = a[b, :, index]                  # [d1, d3]
                n1, n2 = d1, d3
            else:
                plane = a[b, :, :, index]               # [d1, d2]
                n1, n2 = d1, d2
            order = _np.argsort(-plane.ravel())
            tag1 = _np.zeros(n1, bool)
            tag2 = _np.zeros(n2, bool)
            picked = 0
            for f in order:
                i1, i2 = divmod(int(f), n2)
                if tag1[i1] or tag2[i2]:
                    continue
                tag1[i1] = tag2[i2] = True
                picked += 1
                if axis == 1:
                    out[b, :, i1, i2] = 1
                elif axis == 2:
                    out[b, i1, :, i2] = 1
                else:
                    out[b, i1, i2, :] = 1
                if picked == min(n1, n2):
                    break
    return jnp.asarray(out)


register_op("similarity_focus", _similarity_focus_raw)


def similarity_focus(input, axis, indexes, name=None):
    return apply(_similarity_focus_raw, (input,),
                 {"axis": int(axis), "indexes": [int(i) for i in indexes]},
                 differentiable=False, name="similarity_focus")


def _rasterize_polygon_np(poly, x0, y0, x1, y1, M):
    """Point-in-polygon (crossing number) over an M x M grid spanning the
    box [x0,x1] x [y0,y1] — numpy-vectorized over the grid."""
    import numpy as _np
    xs = x0 + (_np.arange(M) + 0.5) * max(x1 - x0, 1e-6) / M
    ys = y0 + (_np.arange(M) + 0.5) * max(y1 - y0, 1e-6) / M
    gx, gy = _np.meshgrid(xs, ys)                       # [M, M]
    inside = _np.zeros((M, M), bool)
    n = poly.shape[0]
    for i in range(n):
        xa, ya = poly[i]
        xb, yb = poly[(i + 1) % n]
        cond = ((ya > gy) != (yb > gy))
        with _np.errstate(divide="ignore", invalid="ignore"):
            xint = xa + (gy - ya) * (xb - xa) / (yb - ya + 1e-12)
        inside ^= cond & (gx < xint)
    return inside


def _generate_mask_labels_raw(rois, roi_labels, gt_polys, poly_lens,
                              gt_classes, resolution=14):
    """Mask R-CNN mask targets (ref operators/detection/
    generate_mask_labels_op.cc, which rasterises COCO polygons per fg
    roi): each fg roi takes the gt polygon whose bounding box overlaps it
    most and rasterises the polygon restricted to the roi into an
    M x M binary grid. Dense contract: gt_polys [G, P, 2] zero-padded
    with poly_lens [G]; outputs (mask_int32 [R, M, M], roi_has_mask [R]).
    Background rois (label <= 0) produce zero masks."""
    import numpy as _np
    r = _np.asarray(rois)
    lab = _np.asarray(roi_labels).reshape(-1)
    polys = _np.asarray(gt_polys)
    plens = _np.asarray(poly_lens).reshape(-1)
    gcls = _np.asarray(gt_classes).reshape(-1)
    R = r.shape[0]
    M = resolution
    masks = _np.zeros((R, M, M), _np.int32)
    has = _np.zeros((R,), _np.int32)
    if polys.shape[0]:
        # gt bbox per polygon
        boxes = _np.zeros((polys.shape[0], 4), _np.float32)
        for g in range(polys.shape[0]):
            p = polys[g, :max(int(plens[g]), 1)]
            boxes[g] = [p[:, 0].min(), p[:, 1].min(),
                        p[:, 0].max(), p[:, 1].max()]
        iou = _iou_corner_np(r, boxes)
        # a roi may only take a mask from a gt of ITS class (ref semantics:
        # mask targets are class-specific) — other classes' IoU is zeroed
        same_cls = gcls[None, :] == lab[:, None]
        iou = _np.where(same_cls, iou, 0.0)
        best = iou.argmax(axis=1)
        for i in range(R):
            if lab[i] <= 0 or iou[i, best[i]] <= 0:
                continue
            g = best[i]
            poly = polys[g, :int(plens[g])]
            m = _rasterize_polygon_np(poly, r[i, 0], r[i, 1],
                                      r[i, 2], r[i, 3], M)
            masks[i] = m.astype(_np.int32)
            has[i] = 1
    return jnp.asarray(masks), jnp.asarray(has)


register_op("generate_mask_labels", _generate_mask_labels_raw)
