"""paddle_tpu.vision (ref python/paddle/vision): model zoo, transforms, datasets."""
from . import models
from . import transforms
from . import datasets
from . import ops
