"""paddle_tpu.distribution — probability distributions.

TPU-native version of the reference distributions
(ref python/paddle/fluid/layers/distributions.py:30,115,260,425,531 —
Distribution/Uniform/Normal/Categorical/MultivariateNormalDiag, and the
paddle 2.x paddle.distribution namespace): sampling draws from the
framework RNG (threefry keys, reproducible under jit) instead of a
per-call CUDA generator; densities are pure jnp so they fuse into
surrounding programs.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import state
from ..framework.tensor import Tensor


def _arr(x, dtype=None):
    if isinstance(x, Tensor):
        a = x._data
    else:
        a = jnp.asarray(x, dtype=jnp.float32 if isinstance(
            x, (int, float, list, tuple)) else None)
    if dtype is not None and a.dtype != dtype:
        a = a.astype(dtype)
    return a


class Distribution:
    """ref distributions.py:30."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._data))

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) (ref distributions.py:115)."""

    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)

    def sample(self, shape=()):
        key = state.next_rng_key()
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(key, shape, dtype=self.low.dtype)
        return Tensor(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        v = _arr(value)
        inside = jnp.logical_and(v >= self.low, v < self.high)
        lp = jnp.where(inside, -jnp.log(self.high - self.low),
                       -jnp.inf)
        return Tensor(lp)

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Normal(Distribution):
    """N(loc, scale^2) (ref distributions.py:260)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def sample(self, shape=()):
        key = state.next_rng_key()
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        z = jax.random.normal(key, shape, dtype=self.loc.dtype)
        return Tensor(self.loc + z * self.scale)

    def log_prob(self, value):
        v = _arr(value)
        var = self.scale * self.scale
        lp = (-((v - self.loc) ** 2) / (2 * var)
              - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))
        return Tensor(lp)

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale)
                      + jnp.zeros_like(self.loc))

    def kl_divergence(self, other):
        """KL(self || other), other Normal (ref distributions.py kl_divergence)."""
        var_a = self.scale ** 2
        var_b = other.scale ** 2
        kl = (jnp.log(other.scale / self.scale)
              + (var_a + (self.loc - other.loc) ** 2) / (2 * var_b) - 0.5)
        return Tensor(kl)


class Categorical(Distribution):
    """Categorical over the last axis of `logits` (ref distributions.py:425)."""

    def __init__(self, logits, name=None):
        self.logits = _arr(logits)
        self._log_p = jax.nn.log_softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        key = state.next_rng_key()
        return Tensor(jax.random.categorical(key, self.logits,
                                             shape=tuple(shape)
                                             + self.logits.shape[:-1]))

    def log_prob(self, value):
        v = _arr(value).astype(jnp.int32)
        if self._log_p.ndim == 1:
            return Tensor(self._log_p[v])
        return Tensor(jnp.take_along_axis(
            self._log_p, v[..., None], axis=-1).squeeze(-1))

    def probs(self, value=None):
        p = jnp.exp(self._log_p)
        if value is None:
            return Tensor(p)
        v = _arr(value).astype(jnp.int32)
        if p.ndim == 1:
            return Tensor(p[v])
        return Tensor(jnp.take_along_axis(p, v[..., None],
                                          axis=-1).squeeze(-1))

    def entropy(self):
        p = jnp.exp(self._log_p)
        return Tensor(-jnp.sum(p * self._log_p, axis=-1))

    def kl_divergence(self, other):
        p = jnp.exp(self._log_p)
        return Tensor(jnp.sum(p * (self._log_p - other._log_p), axis=-1))


class MultivariateNormalDiag(Distribution):
    """Diagonal-covariance MVN (ref distributions.py:531)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)  # diagonal std

    @property
    def _dim(self):
        return self.loc.shape[-1]

    def sample(self, shape=()):
        key = state.next_rng_key()
        z = jax.random.normal(key, tuple(shape) + self.loc.shape,
                              dtype=self.loc.dtype)
        return Tensor(self.loc + z * self.scale)

    def log_prob(self, value):
        v = _arr(value)
        lp = (-0.5 * jnp.sum(((v - self.loc) / self.scale) ** 2, axis=-1)
              - jnp.sum(jnp.log(self.scale), axis=-1)
              - 0.5 * self._dim * math.log(2 * math.pi))
        return Tensor(lp)

    def entropy(self):
        return Tensor(0.5 * self._dim * (1 + math.log(2 * math.pi))
                      + jnp.sum(jnp.log(self.scale), axis=-1))

    def kl_divergence(self, other):
        var_a = self.scale ** 2
        var_b = other.scale ** 2
        kl = 0.5 * jnp.sum(
            var_a / var_b + ((self.loc - other.loc) ** 2) / var_b
            - 1.0 + jnp.log(var_b) - jnp.log(var_a), axis=-1)
        return Tensor(kl)


def kl_divergence(p, q):
    """paddle.distribution.kl_divergence dispatch."""
    return p.kl_divergence(q)
