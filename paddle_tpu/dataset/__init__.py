"""paddle.dataset legacy namespace (ref python/paddle/dataset): reader-
style wrappers over the dataset zoo (offline env: synthetic-backed, same
as vision/text datasets; real files load when paths are provided)."""


def _reader_from(ds):
    def reader():
        for i in range(len(ds)):
            yield tuple(x for x in ds[i])
    return reader


class mnist:
    @staticmethod
    def train():
        from ..vision.datasets import MNIST
        return _reader_from(MNIST(mode="train"))

    @staticmethod
    def test():
        from ..vision.datasets import MNIST
        return _reader_from(MNIST(mode="test"))


def _housing_reader(seed, n):
    import numpy as np
    w = np.random.RandomState(0).randn(13).astype("f4")

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(n):
            x = r.randn(13).astype("f4")
            yield x, np.asarray([x @ w + 0.1 * r.randn()], "f4")
    return reader


class uci_housing:
    @staticmethod
    def train():
        return _housing_reader(1, 404)

    @staticmethod
    def test():
        return _housing_reader(2, 102)


class imdb:
    @staticmethod
    def train(word_idx=None):
        from ..text import Imdb
        return _reader_from(Imdb(mode="train"))

    @staticmethod
    def test(word_idx=None):
        from ..text import Imdb
        return _reader_from(Imdb(mode="test"))
