"""paddle_tpu — a TPU-native deep learning framework with PaddlePaddle's
capability surface, rebuilt on JAX/XLA/Pallas (ref: Yelrose/Paddle at
/root/reference; see SURVEY.md for the layer map this mirrors).

Programming model (ref README.md dual model):
  - dygraph (eager): ops dispatch to XLA-cached executables, tape autograd
    (`Tensor.backward()`).
  - compiled: `paddle_tpu.jit.to_static` / hapi `Model` trace whole train steps
    through jax.jit — the static-graph analog where XLA owns fusion/scheduling.
"""
__version__ = "0.1.0"

from .framework import (  # noqa: F401
    Tensor, Parameter, to_tensor, create_parameter,
    float16, bfloat16, float32, float64, int8, int16, int32, int64, uint8,
    bool_, complex64, complex128,
    CPUPlace, TPUPlace, CUDAPlace, XPUPlace,
    set_device, get_device, seed, set_flags, get_flags, no_grad,
    set_default_dtype, get_default_dtype, is_grad_enabled,
)

from . import framework
from .framework import errors  # noqa: F401  (paddle.errors taxonomy)
from . import ops
from .ops.creation import (  # noqa: F401
    zeros, ones, full, empty, zeros_like, ones_like, full_like, empty_like,
    arange, linspace, logspace, eye, diag, diagflat, tril, triu, meshgrid,
    assign, clone, rand, randn, normal, uniform, randint, randperm, bernoulli,
    multinomial, standard_normal,
)
from .ops.math import (  # noqa: F401
    add, subtract, multiply, divide, floor_divide, remainder, mod, pow,
    maximum, minimum, fmax, fmin, abs, neg, exp, expm1, log, log2, log10,
    log1p, sqrt, rsqrt, square, reciprocal, sin, cos, tan, asin, acos, atan,
    sinh, cosh, tanh, asinh, acosh, atanh, erf, floor, ceil, round, trunc,
    sign, clip, isnan, isinf, isfinite, nan_to_num, sum, mean, prod, max, min,
    amax, amin, logsumexp, std, var, median, argmax, argmin, cumsum, cumprod,
    count_nonzero, matmul, mm, dot, bmm, inner, outer, addmm, kron, trace,
    diagonal, topk, sort, argsort, unique, kthvalue, mode, scale, increment,
    multiplex, atan2, sigmoid, lgamma, digamma, erfinv,
    lerp, heaviside, logit, logaddexp, xlogy, sinc, exp2, rad2deg, deg2rad,
    copysign, nextafter, gcd, lcm, diff, trapezoid, cummax, cummin,
    logcumsumexp, searchsorted, bucketize, renorm, quantile, nanquantile,
    dist, angle, conj, real, imag, complex, polar, sgn, signbit, ldexp,
    hypot, frac, nansum, nanmean, add_n, mv, numel, broadcast_shape,
)
from .ops.linalg import (  # noqa: F401  (also under paddle.linalg)
    cholesky, cross, inverse, norm, histogram, bincount,
)
from .static.control_flow import (  # noqa: F401  (legacy TensorArray API)
    array_write, array_read, array_length, create_array,
)
from .ops.manipulation import (  # noqa: F401
    cast, reshape, reshape_, flatten, transpose, moveaxis, swapaxes, t, concat,
    stack, unstack, split, chunk, unbind, squeeze, unsqueeze, expand,
    broadcast_to, expand_as, tile, repeat_interleave, flip, roll, rot90,
    slice, strided_slice, gather, gather_nd, scatter, scatter_nd,
    scatter_nd_add, index_select, index_sample, where, nonzero, masked_select,
    masked_fill, take_along_axis, put_along_axis, shard_index, one_hot,
    tensordot, as_complex, as_real, crop,
    take, index_add, index_put, masked_scatter, unflatten,
)
from .ops.logic import (  # noqa: F401
    equal, not_equal, greater_than, greater_equal, less_than, less_equal,
    logical_and, logical_or, logical_xor, logical_not, bitwise_and, bitwise_or,
    bitwise_xor, bitwise_not, all, any, isclose, allclose, equal_all,
    is_empty, is_tensor,
)
from .ops import linalg  # noqa: F401

from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import io  # noqa: E402
from . import metric  # noqa: E402
from . import amp  # noqa: E402
from . import jit  # noqa: E402
from . import distributed  # noqa: E402
from . import static  # noqa: E402
from . import vision  # noqa: E402
from . import onnx  # noqa: E402
from . import incubate  # noqa: E402
from . import text  # noqa: E402
from . import distribution  # noqa: E402
from . import quantization  # noqa: E402
from . import utils  # noqa: E402
from . import fluid  # noqa: E402
from . import autograd  # noqa: E402
from . import device  # noqa: E402
from . import reader  # noqa: E402
from . import compat  # noqa: E402
from . import sysconfig  # noqa: E402
from . import inference  # noqa: E402
from . import dataset  # noqa: E402
from . import tensor  # noqa: E402
from .reader import batch  # noqa: E402
from . import rec  # noqa: E402
from .framework.serialization import save, load  # noqa: E402
from .hapi.model import Model, summary  # noqa: E402
from .framework.state import get_flags, set_flags  # noqa: E402,F811
# Registry completeness: every op-registering module is imported by the
# base package, so len(OP_REGISTRY) is ONE number for every import set
# (tests assert the docs match it — see tests/test_registry_count.py).
from . import nlp  # noqa: E402,F401        (llama_attention, rms_norm)
from . import serving  # noqa: E402,F401    (continuous-batching engine)
from .static import quant_pass as _quant_pass  # noqa: E402,F401

# inplace tensor-method variants (ref tensor/manipulation.py *_ APIs);
# one aliasing helper (nn.functional._inplace) owns the slot contract
def scatter_(x, index, updates, overwrite=True, name=None):
    from .nn.functional import _inplace
    return _inplace(x, scatter(x, index, updates, overwrite=overwrite))


def squeeze_(x, axis=None, name=None):
    from .nn.functional import _inplace
    return _inplace(x, squeeze(x, axis=axis))


def unsqueeze_(x, axis, name=None):
    from .nn.functional import _inplace
    return _inplace(x, unsqueeze(x, axis))


def tanh_(x, name=None):
    from .nn.functional import _inplace
    return _inplace(x, tanh(x))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    """ref tensor/random.py gaussian."""
    return normal(mean=mean, std=std, shape=shape)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """ref tensor/to_string.py set_printoptions: Tensor.__repr__ delegates
    to numpy, so numpy's printoptions ARE the framework's print state."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def to_string(x, prefix="Tensor"):
    import numpy as _np
    a = x.numpy() if hasattr(x, "numpy") else _np.asarray(x)
    return (f"{prefix}(shape={list(a.shape)}, dtype={a.dtype}, "
            f"stop_gradient={getattr(x, 'stop_gradient', True)},\n"
            f"       {_np.array2string(a, prefix='       ')})")


# dygraph-mode queries (reference framework.py:182 in_dygraph_mode)
def in_dynamic_mode():
    from .framework import state as _s
    return not _s.is_functional_mode()


in_dygraph_mode = in_dynamic_mode


def disable_static(place=None):
    return None


def enable_static():
    from .static import _enable_static_mode
    _enable_static_mode()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad analog (ref imperative/partial_grad_engine.cc): returns grads
    of `outputs` wrt `inputs` without touching `.grad` slots."""
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    saved = [(t.grad, t.stop_gradient) for t in ins]
    for t in ins:
        t.grad = None
    rg = retain_graph if retain_graph is not None else create_graph
    from .framework import tape as _tape
    sinks = {id(t) for t in ins} if only_inputs else None
    for o in outs:
        _tape.backward(o, retain_graph=bool(rg),
                       create_graph=bool(create_graph),
                       only_accumulate=sinks)
    grads = [t.grad for t in ins]
    for t, (g, sg) in zip(ins, saved):
        t.grad = g
    for g, t in zip(grads, ins):
        if g is None and not allow_unused:
            raise RuntimeError(f"grad: input {t.name} unused in graph "
                               "(pass allow_unused=True to get None)")
    return grads
