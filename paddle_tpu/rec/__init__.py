"""Recommendation model zoo — BASELINE config "Wide&Deep / DeepFM (PS,
sparse)" model families (ref PaddleRec wide_deep/deepfm nets; the core repo
exercises them through the PS trainers, tests/test_ps.py style).

TPU-native: the embedding tables are ordinary dense Parameters for
single-chip / GSPMD training; `wide_deep_sparse_loss` provides the
PS-trainer variant (AsyncPSTrainer / HeterPSTrainer) where embedding rows
come from a host-side sparse table.
"""
from .models import WideDeep, DeepFM, ctr_loss, wide_deep_sparse_loss

__all__ = ["WideDeep", "DeepFM", "ctr_loss", "wide_deep_sparse_loss"]
