"""Wide&Deep and DeepFM (ref PaddleRec models/rank/{wide_deep,deepfm};
the reference trains them through the PS stack — BASELINE config 5).

Both models take
  sparse_ids: int tensor [B, n_fields] of feature ids into one shared
              vocabulary (field offsets pre-applied, the usual PS layout)
  dense_x:    float tensor [B, n_dense] of continuous features
and return logits [B] (binary CTR-style objective).

`wide_deep_sparse_loss` builds the pure-functional variant used by the PS
trainers (AsyncPSTrainer / HeterPSTrainer), where the embedding block comes
from the host sparse table instead of a device Parameter (see
distributed/fleet/heter.py).
"""
import numpy as np
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..ops.manipulation import concat


class _MLP(nn.Layer):
    def __init__(self, in_dim, hidden, act="relu"):
        super().__init__()
        layers = []
        d = in_dim
        for h in hidden:
            layers.append(nn.Linear(d, h))
            layers.append(nn.ReLU())
            d = h
        layers.append(nn.Linear(d, 1))
        self.net = nn.Sequential(*layers)

    def forward(self, x):
        return self.net(x)


class WideDeep(nn.Layer):
    """wide (linear-over-ids) + deep (embedding MLP) joint logit."""

    def __init__(self, vocab_size, emb_dim=8, n_fields=4, n_dense=4,
                 hidden=(64, 32)):
        super().__init__()
        self.vocab_size = vocab_size
        self.emb_dim = emb_dim
        self.n_fields = n_fields
        # wide part: per-id scalar weight == 1-dim embedding
        self.wide_emb = nn.Embedding(vocab_size, 1)
        self.deep_emb = nn.Embedding(vocab_size, emb_dim)
        self.deep_mlp = _MLP(n_fields * emb_dim + n_dense, list(hidden))
        self.bias = self.create_parameter(
            [1], default_initializer=nn.initializer.Constant(0.0))

    def forward(self, sparse_ids, dense_x):
        b = sparse_ids.shape[0]
        wide = self.wide_emb(sparse_ids).reshape([b, self.n_fields]) \
                   .sum(axis=1)
        emb = self.deep_emb(sparse_ids).reshape(
            [b, self.n_fields * self.emb_dim])
        deep_in = concat([emb, dense_x], axis=1)
        deep = self.deep_mlp(deep_in).reshape([b])
        return wide + deep + self.bias


class DeepFM(nn.Layer):
    """FM second-order interactions + deep MLP over shared embeddings
    (ref deepfm_net: first_order + sum-square trick + DNN)."""

    def __init__(self, vocab_size, emb_dim=8, n_fields=4, n_dense=0,
                 hidden=(64, 32)):
        super().__init__()
        self.vocab_size = vocab_size
        self.emb_dim = emb_dim
        self.n_fields = n_fields
        self.first_emb = nn.Embedding(vocab_size, 1)
        self.second_emb = nn.Embedding(vocab_size, emb_dim)
        self.mlp = _MLP(n_fields * emb_dim + n_dense, list(hidden))
        self.bias = self.create_parameter(
            [1], default_initializer=nn.initializer.Constant(0.0))

    def forward(self, sparse_ids, dense_x=None):
        b = sparse_ids.shape[0]
        first = self.first_emb(sparse_ids).reshape([b, self.n_fields]) \
                    .sum(axis=1)
        e = self.second_emb(sparse_ids)          # [B, F, D]
        # FM: 0.5 * sum_d((sum_f e)^2 - sum_f e^2)
        s = e.sum(axis=1)
        fm = 0.5 * (s * s - (e * e).sum(axis=1)).sum(axis=1)
        flat = e.reshape([b, self.n_fields * self.emb_dim])
        deep_in = flat if dense_x is None \
            else concat([flat, dense_x], axis=1)
        deep = self.mlp(deep_in).reshape([b])
        return first + fm + deep + self.bias


def ctr_loss(logits, labels):
    """Binary logistic loss on raw logits (ref log_loss over sigmoid)."""
    return F.binary_cross_entropy_with_logits(logits, labels)


# ---------------------------------------------------------------- PS path

def wide_deep_sparse_loss(n_fields, emb_dim, n_dense, hidden=(64, 32)):
    """Build (params_template, loss_fn) for the PS trainers: the deep
    embedding block comes from the host sparse table (wide weights fold
    into the table's first column). loss_fn(params, urows, inv, dense_x,
    labels) -> scalar; `urows[inv]` = per-(b,field) rows [B*F, 1+emb_dim]
    where col 0 is the wide weight."""
    rng = np.random.RandomState(0)
    d_in = n_fields * emb_dim + n_dense
    params = {"w1": rng.normal(0, 0.05, (d_in, hidden[0])).astype("f4"),
              "b1": np.zeros(hidden[0], "f4"),
              "w2": rng.normal(0, 0.05, (hidden[0], hidden[1])).astype("f4"),
              "b2": np.zeros(hidden[1], "f4"),
              "w3": rng.normal(0, 0.05, (hidden[1], 1)).astype("f4"),
              "b3": np.zeros(1, "f4")}

    def loss_fn(p, urows, inv, dense_x, labels):
        rows = urows[inv]                      # [B*F, 1+emb_dim]
        b = labels.shape[0]
        wide = rows[:, 0].reshape(b, n_fields).sum(axis=1)
        emb = rows[:, 1:].reshape(b, n_fields * emb_dim)
        x = jnp.concatenate([emb, dense_x], axis=1) if n_dense \
            else emb
        h = jnp.maximum(x @ p["w1"] + p["b1"], 0.0)
        h = jnp.maximum(h @ p["w2"] + p["b2"], 0.0)
        logit = (h @ p["w3"] + p["b3"])[:, 0] + wide
        z = jnp.clip(logit, -30, 30)
        return jnp.mean(jnp.log1p(jnp.exp(-jnp.abs(z)))
                        + jnp.maximum(z, 0.0) - z * labels)

    return params, loss_fn
