"""ptlint core: rule registry, file context, suppression handling.

A rule is a class with an `id`, a `check(ctx)` generator yielding
`Finding`s, and a one-line `rationale`. Rules register themselves with
`@register`; `lint_paths` parses each file ONCE and hands the shared
`FileContext` to every (selected) rule, so a full-repo run stays
AST-parse-bound (~hundreds of files, well under the 10 s budget).

Suppressions: a `# ptlint: disable=rule-a,rule-b` trailing comment on
the flagged line silences those rules there; bare `# ptlint: disable`
silences every rule on that line. Messages carry no line numbers so a
finding's identity (rule, path, message) survives unrelated edits —
that identity is what the baseline (baseline.py) matches on.
"""
import ast
import os
import re


class Finding:
    """One lint hit. `message` must be stable across unrelated edits
    (no line numbers / volatile state inside) — the baseline fingerprint
    is (rule, path, message)."""

    __slots__ = ("rule", "path", "line", "col", "message")

    def __init__(self, rule, path, line, col, message):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.message = message

    @property
    def fingerprint(self):
        return f"{self.rule}::{self.path}::{self.message}"

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}

    def render(self):
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.message}"

    def __repr__(self):
        return f"Finding({self.render()!r})"


_SUPPRESS_RE = re.compile(
    r"#\s*ptlint:\s*disable(?:=([A-Za-z0-9_,\- ]+))?")


def parse_suppressions(src):
    """{lineno: frozenset(rule_ids) | None} — None means all rules."""
    out = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        if m.group(1) is None:
            out[i] = None
        else:
            rules = frozenset(
                r.strip() for r in m.group(1).split(",") if r.strip())
            out[i] = rules or None
    return out


class FileContext:
    """Everything rules need about one file, parsed once."""

    def __init__(self, path, rel, src, tree, repo_root):
        self.path = path            # absolute
        self.rel = rel              # repo-relative, '/'-separated
        self.src = src
        self.tree = tree
        self.repo_root = repo_root
        self.suppressions = parse_suppressions(src)
        # a suppression on a `def`/`class` line covers the whole body
        # (one annotation instead of one per finding — trace-time
        # precomputation helpers use this)
        self.ranges = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) \
                    and node.lineno in self.suppressions:
                self.ranges.append((node.lineno, node.end_lineno,
                                    self.suppressions[node.lineno]))
        self._cache = {}            # rule modules share derived analyses

    def cached(self, key, build):
        if key not in self._cache:
            self._cache[key] = build()
        return self._cache[key]

    def suppressed(self, line, rule_id):
        rules = self.suppressions.get(line, False)
        if rules is not False and (rules is None or rule_id in rules):
            return True
        for start, end, rules in self.ranges:
            if start <= line <= end \
                    and (rules is None or rule_id in rules):
                return True
        return False

    def finding(self, rule_id, node, message):
        return Finding(rule_id, self.rel, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


class Rule:
    id = None
    rationale = ""

    def check(self, ctx):
        raise NotImplementedError
        yield  # pragma: no cover


RULES = {}


def register(cls):
    """Class decorator: instantiate and add to the registry."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    RULES[inst.id] = inst
    return cls


def iter_py_files(paths):
    for root in paths:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in ("__pycache__", ".git", ".jax_cache"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_file(path, repo_root, select=None):
    """Findings for one file (suppressions already applied).

    A file that fails to parse (or read/decode) yields one
    `parse-error` finding instead of aborting the run — the CLI still
    exits 1 on it."""
    rel = os.path.relpath(os.path.abspath(path),
                          os.path.abspath(repo_root)).replace(os.sep, "/")
    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    except (OSError, UnicodeDecodeError) as e:
        return [Finding("parse-error", rel, 1, 0,
                        f"cannot read: {type(e).__name__}")]
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("parse-error", rel, e.lineno or 1, 0,
                        f"cannot parse: {e.msg}")]
    ctx = FileContext(path, rel, src, tree, repo_root)
    findings = []
    for rule_id, rule in sorted(RULES.items()):
        if select is not None and rule_id not in select:
            continue
        for fd in rule.check(ctx):
            if not ctx.suppressed(fd.line, fd.rule):
                findings.append(fd)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_paths(paths, repo_root, select=None):
    if select is not None:
        unknown = set(select) - set(RULES)
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
    findings = []
    for path in iter_py_files(paths):
        findings.extend(lint_file(path, repo_root, select))
    return findings
