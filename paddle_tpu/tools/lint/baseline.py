"""ptlint baseline: grandfathered findings, committed next to the CLI.

The baseline is a JSON list of finding identities — (rule, path,
message) plus an occurrence count and a REQUIRED one-line justification
per entry. `diff` subtracts baselined occurrences from a run's
findings; anything left over is new and fails the lint. Counts matter:
a baselined fingerprint hides exactly `count` occurrences, so adding a
second instance of a grandfathered pattern to the same file still
fails.

`update` rewrites the baseline from a run, preserving justifications of
surviving entries and stamping new ones with a TODO marker the clean-run
check rejects — a baseline entry cannot land undocumented.
"""
import collections
import json

TODO_JUSTIFICATION = "TODO: justify this grandfathered finding"


def load(path):
    """-> list of entry dicts (rule/path/message/count/justification).
    Missing file -> empty baseline."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return []
    if isinstance(data, dict):
        data = data.get("findings", [])
    out = []
    for e in data:
        out.append({
            "rule": e["rule"], "path": e["path"], "message": e["message"],
            "count": int(e.get("count", 1)),
            "justification": e.get("justification",
                                   TODO_JUSTIFICATION),
        })
    return out


def _key(entry_or_finding):
    e = entry_or_finding
    if isinstance(e, dict):
        return (e["rule"], e["path"], e["message"])
    return (e.rule, e.path, e.message)


def diff(findings, entries):
    """(new_findings, suppressed_count): subtract up to `count`
    occurrences of each baselined identity; later (higher-line)
    occurrences survive as new."""
    budget = collections.Counter()
    for e in entries:
        budget[_key(e)] += e["count"]
    new, suppressed = [], 0
    for fd in findings:         # lint_paths yields line-sorted findings
        k = _key(fd)
        if budget[k] > 0:
            budget[k] -= 1
            suppressed += 1
        else:
            new.append(fd)
    return new, suppressed


def undocumented(entries):
    """Entries whose justification is missing/TODO — the clean-run
    contract rejects these even when the diff is empty."""
    return [e for e in entries
            if not e.get("justification")
            or e["justification"] == TODO_JUSTIFICATION]


def gate(findings, entries):
    """The shared clean-run verdict both ptlint and jxaudit exit on:
    (new_findings, suppressed_count, undocumented_entries, clean).
    One implementation so the two CLIs' exit contracts cannot drift."""
    new, suppressed = diff(findings, entries)
    undoc = undocumented(entries)
    return new, suppressed, undoc, (not new and not undoc)


def update(findings, old_entries, path, keep=()):
    """Write a fresh baseline covering exactly `findings`, carrying
    justifications over from `old_entries` where the identity survives.
    `keep` preserves entries a SCOPED run (--select / narrowed paths)
    could not have reproduced — without it a partial run would silently
    delete every out-of-scope grandfathered entry and its justification."""
    just = {_key(e): e["justification"] for e in old_entries}
    counts = collections.Counter(_key(fd) for fd in findings)
    entries = [dict(e) for e in keep if _key(e) not in counts]
    for (rule, rel, message), count in sorted(counts.items()):
        entries.append({
            "rule": rule, "path": rel, "message": message, "count": count,
            "justification": just.get((rule, rel, message),
                                      TODO_JUSTIFICATION),
        })
    entries.sort(key=_key)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": entries}, f, indent=2,
                  sort_keys=True)
        f.write("\n")
    return entries
