"""Shared AST helpers for ptlint rules."""
import ast


def build_parents(tree):
    """{child_node: parent_node} for ancestor walks (loop/with/def
    containment). Built once per file via ctx.cached."""
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def parents_of(ctx):
    return ctx.cached("parents", lambda: build_parents(ctx.tree))


def ancestors(node, parents):
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


def last_name(node):
    """Terminal identifier of a Name/Attribute chain ('jax.jit' -> 'jit',
    'jit' -> 'jit'); None for anything else."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted(node):
    """'a.b.c' for nested Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def param_names(fn):
    a = fn.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def binds(target):
    """Names a *binding* target introduces. `x = ...`, `x, y = ...` bind;
    `d[k] = ...` and `o.a = ...` mutate an existing object and bind
    NOTHING — treating them as bindings would hide exactly the writes
    the lock/trace rules exist to catch."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            yield from binds(el)
    elif isinstance(target, ast.Starred):
        yield from binds(target.value)


def assigned_names(fn):
    """Plain-Name bindings inside a function def (its own subtree,
    nested defs included — over-approximate shadow detection)."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                out.update(binds(t))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
            out.update(binds(node.target))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    out.update(binds(item.optional_vars))
        elif isinstance(node, FUNC_DEFS):
            if node is not fn:
                out.add(node.name)
            out.update(param_names(node))
    return out


def global_names(fn):
    """Names declared `global` anywhere inside the function subtree."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                    ast.SetComp, ast.DictComp)
MUTABLE_FACTORIES = {"list", "dict", "set", "defaultdict", "OrderedDict",
                     "deque", "Counter", "bytearray"}


def is_mutable_value(node):
    """True for expressions that construct a mutable container."""
    if isinstance(node, MUTABLE_LITERALS):
        return True
    if isinstance(node, ast.Call) and \
            last_name(node.func) in MUTABLE_FACTORIES:
        return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        # [0] * n / n * [0]
        return is_mutable_value(node.left) or is_mutable_value(node.right)
    return False
