"""Importing this package registers every built-in ptlint rule."""
from . import (alert_rules, chaos_guard, event_kinds,  # noqa: F401
               hygiene, locks, mesh_axes, metric_names, tracer)
