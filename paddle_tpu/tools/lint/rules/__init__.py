"""Importing this package registers every built-in ptlint rule."""
from . import hygiene, locks, metric_names, tracer  # noqa: F401
