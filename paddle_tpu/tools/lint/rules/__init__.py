"""Importing this package registers every built-in ptlint rule."""
from . import (alert_rules, chaos_guard, hygiene, locks,  # noqa: F401
               metric_names, tracer)
