"""Importing this package registers every built-in ptlint rule."""
from . import chaos_guard, hygiene, locks, metric_names, tracer  # noqa: F401
