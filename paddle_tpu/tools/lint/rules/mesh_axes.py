"""mesh-axis-name: every string-literal mesh axis is a declared axis.

A PartitionSpec / collective naming an axis the mesh does not have is
the classic silent-replication typo: GSPMD treats the unknown name as
"don't partition", the program compiles, and the only symptom is N
copies of the tensor (shaudit's accidental-replication rule catches it
at compile level — this rule catches the typo at the source).

Allowed axis names per file are the union of:

  * the canonical ``*_AXIS`` constants declared in
    ``paddle_tpu/distributed/mesh.py`` (dp/mp/pp/sp/ep — read from that
    file's AST so this rule cannot drift from the registry of record);
  * axes the FILE ITSELF declares: string literals inside ``Mesh(...)``
    / ``make_mesh(...)`` call arguments (jax's positional axis-name
    tuples and this repo's ``make_mesh({'dp': 8})`` dict keys both
    resolve), plus the file's own module-level ``*_AXIS = "..."``
    constants.

Checked sites: string literals in ``PartitionSpec(...)`` / ``P(...)``
positional args (nested tuples included) and in ``axis_name=`` /
``axis_names=`` keywords of any call. Dynamically-built names are out
of scope — the same escape hatch the metric-name rule leaves.
"""
import ast
import os

from ..core import Rule, register
from ..astutil import last_name

#: fallback when distributed/mesh.py can't be read — the canonical five
#: as of when this rule was written
FALLBACK_AXES = frozenset({"dp", "mp", "pp", "sp", "ep"})

MESH_CTORS = ("Mesh", "make_mesh")
SPEC_CTORS = ("PartitionSpec", "P")

_canonical_cache = {}


def canonical_axes(repo_root):
    """The ``*_AXIS`` string constants of distributed/mesh.py."""
    if repo_root in _canonical_cache:
        return _canonical_cache[repo_root]
    path = os.path.join(repo_root, "paddle_tpu", "distributed", "mesh.py")
    axes = set()
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.endswith("_AXIS") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                axes.add(node.value.value)
    except (OSError, SyntaxError):
        pass
    out = frozenset(axes) or FALLBACK_AXES
    _canonical_cache[repo_root] = out
    return out


def _strings_in(node):
    """String constants anywhere under an expression node."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub


def declared_axes(tree):
    """Axes this file declares: Mesh/make_mesh call literals + its own
    module-level *_AXIS constants."""
    axes = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.endswith("_AXIS") \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            axes.add(node.value.value)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and last_name(node.func) in MESH_CTORS):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Dict):        # make_mesh({'dp': 8})
                for k in arg.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        axes.add(k.value)
            elif isinstance(arg, (ast.Tuple, ast.List, ast.Set,
                                  ast.Constant)):
                for s in _strings_in(arg):
                    axes.add(s.value)
    return axes


def axis_literal_sites(tree):
    """Yield (node, axis_string) for every checked literal site."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if last_name(node.func) in SPEC_CTORS:
            for arg in node.args:
                for s in _strings_in(arg):
                    yield s, s.value
        for kw in node.keywords:
            if kw.arg == "axis_name":
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    yield kw.value, kw.value.value
            elif kw.arg == "axis_names":
                for s in _strings_in(kw.value):
                    yield s, s.value


@register
class MeshAxisName(Rule):
    id = "mesh-axis-name"
    rationale = ("a PartitionSpec/collective naming an axis the mesh "
                 "does not declare compiles to silent full replication "
                 "instead of an error; axis literals must come from "
                 "distributed/mesh.py's *_AXIS registry or a mesh the "
                 "file itself constructs.")

    def check(self, ctx):
        allowed = canonical_axes(ctx.repo_root) \
            | ctx.cached("declared_axes",
                         lambda: declared_axes(ctx.tree))
        for node, axis in axis_literal_sites(ctx.tree):
            if axis not in allowed:
                yield ctx.finding(
                    self.id, node,
                    f"axis name {axis!r} is not a declared mesh axis "
                    f"(known: {', '.join(sorted(allowed))}); typo'd "
                    "axes shard nothing and replicate silently")
