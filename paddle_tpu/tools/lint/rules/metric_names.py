"""metric-name: every literal metric name at a monitor/telemetry call
site is snake_case AND cataloged in docs/observability.md.

The doc IS the metric registry of record — adding a metric means
documenting it, and /metrics cannot silently grow undocumented or
Prometheus-hostile names. Simple module-level NAME = "literal"
constants are resolved (serving/metrics.py declares its monitor keys
that way); dynamic names are out of scope.  (This rule subsumed the
retired scripts/check_metric_names.py standalone linter.)
"""
import ast
import os
import re

from ..core import Rule, register
from ..astutil import last_name

METRIC_FUNCS = {"stat_add", "stat_set", "stat_max", "stat_get",
                "counter", "gauge", "histogram",
                "Counter", "Gauge", "Histogram"}
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
BACKTICK_RE = re.compile(r"`([A-Za-z0-9_]+)`")

_CATALOG_CACHE = {}      # path -> (mtime_ns, names)


def catalog_path(repo_root):
    return os.path.join(repo_root, "docs", "observability.md")


def registered_names(repo_root):
    """Allowlist: every backticked identifier in docs/observability.md.
    None (not empty set) when the catalog is missing — rules and the
    shim distinguish 'no registry here' from 'registry rejects this'.
    Cached per (path, mtime), so a long-lived process that edits the
    catalog between lint_paths() calls sees the fresh registry."""
    path = os.path.abspath(catalog_path(repo_root))
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        _CATALOG_CACHE.pop(path, None)
        return None
    cached = _CATALOG_CACHE.get(path)
    if cached is None or cached[0] != mtime:
        try:
            with open(path, encoding="utf-8") as f:
                names = set(BACKTICK_RE.findall(f.read()))
        except OSError:
            return None
        _CATALOG_CACHE[path] = cached = (mtime, names)
    return cached[1]


def module_consts(tree):
    """Module-level NAME = "literal" string assignments."""
    consts = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value
    return consts


def metric_call_sites(tree):
    """Yield (node, metric_name) for every lintable call in the tree."""
    consts = module_consts(tree)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and last_name(node.func) in METRIC_FUNCS and node.args):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            yield node, arg.value
        elif isinstance(arg, ast.Name) and arg.id in consts:
            yield node, consts[arg.id]


@register
class MetricName(Rule):
    id = "metric-name"
    rationale = ("docs/observability.md is the metric registry of "
                 "record; undocumented or non-snake_case names corrupt "
                 "the /metrics contract silently.")

    def check(self, ctx):
        allow = registered_names(ctx.repo_root)
        for node, name in metric_call_sites(ctx.tree):
            if not NAME_RE.match(name):
                yield ctx.finding(
                    self.id, node,
                    f"metric name {name!r} is not snake_case "
                    "([a-z][a-z0-9_]*)")
            elif allow is not None and name not in allow:
                yield ctx.finding(
                    self.id, node,
                    f"metric name {name!r} is not registered in "
                    "docs/observability.md")
