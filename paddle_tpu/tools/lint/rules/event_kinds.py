"""event-kind-documented: every literal `kind=` at a flight-recorder
fault site or black-box hop site is declared in the owning module's
kind tuple AND cataloged in docs/observability.md.

Same contract as metric-name and alert-rule-documented, for the
journal planes: `utils/flight_recorder.py` declares `FAULT_KINDS` (the
closed vocabulary of `fault` events) and `serving/blackbox.py` declares
`HOP_KINDS` (the fleet-hop vocabulary of the black-box journal).  A
kind invented at a call site but absent from the tuples is invisible to
the runlog summarizer's rollups and to replay; a kind absent from the
doc leaves an operator grepping a journal with no schema to look up.
Kinds are read from the first positional argument or the `kind=`
keyword of `.fault(...)` / `._fault(...)` / `.hop(...)` calls, with
module-level string constants resolved; dynamically-built kinds (the
router's "replica_" + reason family, the scheduler's taxonomy fan-in)
are out of scope, the same escape hatch the sibling rules leave.
"""
import ast
import os
import re

from ..core import Rule, register
from ..astutil import last_name
from .metric_names import module_consts, registered_names

KIND_RE = re.compile(r"^[a-z][a-z0-9_]*$")

#: method name -> (source module, tuple names) the kind must appear in
KIND_METHODS = {
    "fault": ("paddle_tpu/utils/flight_recorder.py", ("FAULT_KINDS",)),
    "_fault": ("paddle_tpu/utils/flight_recorder.py", ("FAULT_KINDS",)),
    "hop": ("paddle_tpu/serving/blackbox.py", ("HOP_KINDS",)),
}

_KINDS_CACHE = {}        # path -> (mtime_ns, {tuple_name: frozenset})


def _declared_in(repo_root, rel_path, tuple_names):
    """The union of the named module-level string tuples in rel_path,
    or None when the module is missing/unparseable — rules distinguish
    'no vocabulary here' from 'vocabulary rejects this'.  Cached per
    (path, mtime) like the docs catalog."""
    path = os.path.abspath(os.path.join(repo_root, rel_path))
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        _KINDS_CACHE.pop(path, None)
        return None
    cached = _KINDS_CACHE.get(path)
    if cached is None or cached[0] != mtime:
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return None
        tuples = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Tuple):
                vals = [e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]
                tuples[node.targets[0].id] = frozenset(vals)
        _KINDS_CACHE[path] = cached = (mtime, tuples)
    out = set()
    found = False
    for name in tuple_names:
        vals = cached[1].get(name)
        if vals is not None:
            found = True
            out.update(vals)
    return out if found else None


def kind_sites(tree):
    """Yield (node, method, kind) for every resolvable fault/hop call."""
    consts = module_consts(tree)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in KIND_METHODS):
            continue
        arg = node.args[0] if node.args else None
        if arg is None:
            for kw in node.keywords:
                if kw.arg == "kind":
                    arg = kw.value
                    break
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            yield node, node.func.attr, arg.value
        elif isinstance(arg, ast.Name) and arg.id in consts:
            yield node, node.func.attr, consts[arg.id]


@register
class EventKindDocumented(Rule):
    id = "event-kind-documented"
    rationale = ("the recorder kind tuples and docs/observability.md "
                 "are the journal schema of record; an undeclared kind "
                 "is invisible to the runlog rollups and to incident "
                 "replay.")

    def check(self, ctx):
        allow = registered_names(ctx.repo_root)
        for node, method, kind in kind_sites(ctx.tree):
            rel_path, tuple_names = KIND_METHODS[method]
            declared = _declared_in(ctx.repo_root, rel_path, tuple_names)
            if not KIND_RE.match(kind):
                yield ctx.finding(
                    self.id, node,
                    f"event kind {kind!r} is not snake_case "
                    "([a-z][a-z0-9_]*)")
            elif declared is not None and kind not in declared:
                yield ctx.finding(
                    self.id, node,
                    f"event kind {kind!r} is not declared in "
                    f"{'/'.join(tuple_names)} of {rel_path}")
            elif allow is not None and kind not in allow:
                yield ctx.finding(
                    self.id, node,
                    f"event kind {kind!r} is not documented in "
                    "docs/observability.md")
