"""Tracer-safety rules: host-sync-in-trace and recompile-hazard.

Both rules share one per-file analysis: the set of TRACED function
defs — functions that jax traces and compiles, so their Python bodies
run once per compilation, not once per call, and any host interaction
inside them is either a silent no-op, a per-step device->host stall, or
a recompile trigger ("Operator Fusion in XLA" finds exactly these two
pathologies dominating JAX performance regressions).

Traced roots, module-locally:
  * defs decorated with jit/pjit/pmap (directly, as a call, or through
    functools.partial(jax.jit, ...));
  * local function names passed to jit/pjit/pmap/grad/value_and_grad/
    vmap/checkpoint/remat or to lax control flow (scan/cond/while_loop/
    fori_loop/switch) — `self._compiled = jax.jit(_step, ...)` marks
    `_step`;
  * calls made inside a lambda handed to one of those wrappers.

From the roots the analysis closes transitively over module-local
callees by name (decode_wave -> helper -> ...). Cross-module reachability
is out of scope — the hot subsystems keep their traced helpers local,
which is also the layout this rule rewards.
"""
import ast

from ..core import Rule, register
from .. import astutil
from ..astutil import FUNC_DEFS, last_name

TRACE_WRAPPERS = {"jit", "pjit", "pmap"}
TRACE_CONSUMERS = TRACE_WRAPPERS | {
    "grad", "value_and_grad", "vmap", "checkpoint", "remat",
    "scan", "cond", "while_loop", "fori_loop", "switch",
    "custom_vjp", "custom_jvp",
}

# attribute calls that force a device->host transfer / sync
SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# module.attr calls that materialize traced values on host
HOST_MATERIALIZERS = {("np", "asarray"), ("np", "array"),
                      ("numpy", "asarray"), ("numpy", "array"),
                      ("onp", "asarray"), ("onp", "array")}
# shape/metadata accesses that make a float()/int() cast trace-safe
STATIC_ATTRS = {"shape", "ndim", "size", "itemsize", "dtype", "maxlen"}
STATIC_FUNCS = {"len", "range", "ord", "min", "max", "round", "prod",
                "id", "hash", "isinstance", "getattr"}


def _is_trace_wrapper(node, names):
    """`node` (a decorator or call func) denotes one of `names`?"""
    if last_name(node) in names:
        return True
    if isinstance(node, ast.Call):
        # @jax.jit(...) / @partial(jax.jit, static_argnums=...)
        if last_name(node.func) in names:
            return True
        if last_name(node.func) == "partial" and node.args \
                and last_name(node.args[0]) in names:
            return True
    return False


def _local_defs(tree):
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, FUNC_DEFS):
            defs.setdefault(node.name, []).append(node)
    return defs


def _enclosing_fn(node, parents):
    for anc in astutil.ancestors(node, parents):
        if isinstance(anc, FUNC_DEFS):
            return anc
    return None


def _resolve(name, ref_node, defs, parents):
    """Defs a bare-Name reference plausibly binds to. A name defined in
    a function enclosing the reference shadows same-named defs elsewhere
    (ServingEngine's traced `decode_wave` closure vs. its host-side
    `decode_wave` method) — prefer lexically-visible candidates."""
    cands = defs.get(name, [])
    if len(cands) < 2:
        return cands
    chain = set()
    for anc in astutil.ancestors(ref_node, parents):
        if isinstance(anc, FUNC_DEFS):
            chain.add(anc)
    scoped = [d for d in cands if _enclosing_fn(d, parents) in chain
              and _enclosing_fn(d, parents) is not None]
    return scoped or cands


def traced_analysis(ctx):
    """-> (traced_defs: set of def nodes, jit_calls: list of jit/pjit
    Call nodes). Cached on the file context; both rules consume it.
    Only bare-Name references resolve to local defs — `jnp.searchsorted`
    must not mark a same-named module wrapper as traced."""
    def build():
        tree = ctx.tree
        defs = _local_defs(tree)
        parents = astutil.parents_of(ctx)
        roots, jit_calls = [], []
        for node in ast.walk(tree):
            if isinstance(node, FUNC_DEFS):
                if any(_is_trace_wrapper(d, TRACE_WRAPPERS)
                       for d in node.decorator_list):
                    roots.append(node)
            elif isinstance(node, ast.Call) \
                    and last_name(node.func) in TRACE_CONSUMERS:
                if last_name(node.func) in TRACE_WRAPPERS:
                    jit_calls.append(node)
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        roots.extend(_resolve(arg.id, node, defs, parents))
                    elif isinstance(arg, ast.Lambda):
                        for sub in ast.walk(arg.body):
                            if isinstance(sub, ast.Call) \
                                    and isinstance(sub.func, ast.Name):
                                roots.extend(_resolve(sub.func.id, sub,
                                                      defs, parents))
        traced = set()
        work = list(roots)
        while work:
            fn = work.pop()
            if fn in traced:
                continue
            traced.add(fn)
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Name):
                    for cand in _resolve(sub.func.id, sub, defs, parents):
                        if cand not in traced:
                            work.append(cand)
        return traced, jit_calls

    return ctx.cached("traced_analysis", build)


def outermost_traced(ctx):
    """Traced defs that are not nested inside another traced def —
    walking only these visits every traced statement exactly once."""
    traced, _ = traced_analysis(ctx)
    parents = astutil.parents_of(ctx)
    out = []
    for fn in traced:
        if not any(a in traced for a in astutil.ancestors(fn, parents)):
            out.append(fn)
    return sorted(out, key=lambda n: n.lineno)


def _is_static_expr(node):
    """Expression whose value is known at trace time (shapes, lengths,
    python constants) — casting those to float/int/bool is fine."""
    if isinstance(node, ast.Constant):
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in STATIC_ATTRS:
            return True
        if isinstance(sub, ast.Call) \
                and last_name(sub.func) in STATIC_FUNCS:
            return True
    return False


@register
class HostSyncInTrace(Rule):
    id = "host-sync-in-trace"
    rationale = ("Device->host transfers (float()/int()/.item()/"
                 "np.asarray) inside jit-traced code stall the device "
                 "pipeline every step, and print() runs at trace time "
                 "only — both break the compiled hot path silently.")

    def check(self, ctx):
        for fn in outermost_traced(ctx):
            yield from self._scan(ctx, fn)

    @staticmethod
    def _is_config_flag(ctx, call, arg):
        """float()/int()/bool() on a parameter whose default is a python
        constant — a config flag, static at trace time, not a tracer."""
        if not isinstance(arg, ast.Name):
            return False
        parents = astutil.parents_of(ctx)
        owner = _enclosing_fn(call, parents)
        while owner is not None:
            a = owner.args
            pos = list(a.posonlyargs) + list(a.args)
            for param, default in zip(pos[len(pos) - len(a.defaults):],
                                      a.defaults):
                if param.arg == arg.id \
                        and isinstance(default, ast.Constant):
                    return True
            for param, default in zip(a.kwonlyargs, a.kw_defaults):
                if param.arg == arg.id \
                        and isinstance(default, ast.Constant):
                    return True
            if arg.id in astutil.param_names(owner):
                return False        # a non-defaulted param: assume traced
            owner = _enclosing_fn(owner, parents)
        return False

    def _scan(self, ctx, fn):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = last_name(node.func)
            if callee == "print":
                yield ctx.finding(
                    self.id, node,
                    f"print() inside traced function '{fn.name}' runs at "
                    "trace time only; use jax.debug.print or hoist it")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in SYNC_METHODS:
                yield ctx.finding(
                    self.id, node,
                    f".{node.func.attr}() inside traced function "
                    f"'{fn.name}' forces a device->host sync per step")
            elif callee == "device_get":
                yield ctx.finding(
                    self.id, node,
                    f"jax.device_get inside traced function '{fn.name}' "
                    "forces a device->host transfer per step")
            elif isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and (node.func.value.id, node.func.attr) \
                    in HOST_MATERIALIZERS:
                yield ctx.finding(
                    self.id, node,
                    f"{node.func.value.id}.{node.func.attr}() inside "
                    f"traced function '{fn.name}' materializes a traced "
                    "value on host (use jnp, or hoist the conversion)")
            elif callee in ("float", "int", "bool") \
                    and isinstance(node.func, ast.Name) \
                    and len(node.args) == 1 and not node.keywords \
                    and not _is_static_expr(node.args[0]) \
                    and not self._is_config_flag(ctx, node, node.args[0]):
                yield ctx.finding(
                    self.id, node,
                    f"{callee}() on a (possibly traced) value inside "
                    f"traced function '{fn.name}' concretizes at trace "
                    "time or syncs; keep it a jax array")


# positional parameter names marking replace-each-call state a jit
# wrapper could donate — THE shared vocabulary with the program-level
# check (one definition, so the two rules cannot drift; jxaudit's
# module bodies import nothing heavier than what the paddle_tpu
# package import already paid for)
from ...jxaudit.rules import STATE_ARG_NAMES as STATE_PARAM_NAMES


@register
class DonateHint(Rule):
    id = "donate-hint"
    rationale = ("A jit/pjit call site threading large state trees "
                 "(KV caches, optimizer state, gradient accumulators) "
                 "without any donate_argnums makes every call "
                 "transiently hold two HBM copies of that state; "
                 "jxaudit's donation rules (scripts/jxaudit.py) are "
                 "the authoritative program-level check.")

    def check(self, ctx):
        tree = ctx.tree
        defs = _local_defs(tree)
        parents = astutil.parents_of(ctx)
        _, jit_calls = traced_analysis(ctx)
        for call in jit_calls:
            if any(kw.arg is None or (kw.arg and "donate" in kw.arg)
                   for kw in call.keywords):
                # declares a donation — or splats **kwargs, which may
                # carry one we can't see: unknown, don't cry wolf
                continue
            if not call.args or not isinstance(call.args[0], ast.Name):
                continue
            for cand in _resolve(call.args[0].id, call, defs, parents):
                state = sorted(set(astutil.param_names(cand))
                               & STATE_PARAM_NAMES)
                if state:
                    yield ctx.finding(
                        self.id, call,
                        f"jit({cand.name}) threads state arg(s) "
                        f"{', '.join(state)} with no donate_argnums: "
                        "each call transiently doubles that state in "
                        "HBM; donate it (authoritative program-level "
                        "check: scripts/jxaudit.py)")
                    break


def _loop_bound(loop):
    """Names (re)bound inside a loop body (incl. the loop target)."""
    out = set()
    for node in ast.walk(loop):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    return out


def _static_indices(call):
    """(argnums: set[int], argnames: set[str]) from a jit call's
    static_argnums/static_argnames keywords (literal forms only)."""
    nums, names = set(), set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, int):
                    nums.add(sub.value)
        elif kw.arg == "static_argnames":
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    names.add(sub.value)
    return nums, names


def _target_key(parents, call):
    """Where the jit-wrapped callable lands: Assign target Name ('f') or
    attribute ('.attr' for self._f = jax.jit(...)); None otherwise."""
    parent = parents.get(call)
    # unwrap instrument_jit(jax.jit(...), label)-style wrappers
    while isinstance(parent, ast.Call):
        call = parent
        parent = parents.get(parent)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        tgt = parent.targets[0]
        if isinstance(tgt, ast.Name):
            return ("name", tgt.id)
        if isinstance(tgt, ast.Attribute):
            return ("attr", tgt.attr)
    return None


@register
class RecompileHazard(Rule):
    id = "recompile-hazard"
    rationale = ("A jit wrapper built per iteration, a traced function "
                 "mutating (or formatting) Python state, or an unhashable "
                 "value in a static argument each force XLA to retrace/"
                 "recompile silently — the dominant JAX perf pathology.")

    def check(self, ctx):
        parents = astutil.parents_of(ctx)
        traced, jit_calls = traced_analysis(ctx)
        yield from self._jit_in_loop(ctx, parents, jit_calls)
        yield from self._jit_on_method(ctx, parents)
        yield from self._static_arg_literals(ctx, parents, jit_calls)
        module_mutables = self._module_mutables(ctx)
        for fn in outermost_traced(ctx):
            yield from self._trace_side_effects(ctx, fn, module_mutables)

    # --- jax.jit(...) evaluated inside a loop -> new wrapper, new cache
    def _jit_in_loop(self, ctx, parents, jit_calls):
        for call in jit_calls:
            for anc in astutil.ancestors(call, parents):
                if isinstance(anc, FUNC_DEFS + (ast.Lambda,)):
                    break
                if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                    # jitting a DIFFERENT function each iteration (a
                    # bench sweep over CASES) is one compile per function
                    # — only a loop-invariant target is the hazard
                    if call.args and isinstance(call.args[0], ast.Name) \
                            and call.args[0].id in _loop_bound(anc):
                        break
                    yield ctx.finding(
                        self.id, call,
                        "jit wrapper constructed inside a loop: every "
                        "iteration builds a fresh callable with an empty "
                        "compile cache; hoist the jit() out of the loop")
                    break

    # --- @jax.jit on an instance method retraces per instance
    def _jit_on_method(self, ctx, parents):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, FUNC_DEFS):
                continue
            if not any(_is_trace_wrapper(d, TRACE_WRAPPERS)
                       for d in node.decorator_list):
                continue
            args = node.args.posonlyargs + node.args.args
            if args and args[0].arg in ("self", "cls") \
                    and isinstance(parents.get(node), ast.ClassDef):
                yield ctx.finding(
                    self.id, node,
                    f"@jit on method '{node.name}': self is a jit "
                    "argument, so every instance (and mutation) "
                    "retraces; jit a closure in __init__ instead")

    # --- list/dict/set literals fed to static argument positions
    def _static_arg_literals(self, ctx, parents, jit_calls):
        targets = {}        # key -> (argnums, argnames)
        for call in jit_calls:
            nums, names = _static_indices(call)
            if not nums and not names:
                continue
            key = _target_key(parents, call)
            if key is not None:
                targets[key] = (nums, names)
            parent = parents.get(call)
            if isinstance(parent, ast.Call) and parent.func is call:
                # jax.jit(f, static_argnums=...)(args) called in place
                yield from self._check_static_call(ctx, parent, nums,
                                                   names)
        if not targets:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name):
                key = ("name", node.func.id)
            elif isinstance(node.func, ast.Attribute):
                key = ("attr", node.func.attr)
            else:
                continue
            if key in targets:
                nums, names = targets[key]
                yield from self._check_static_call(ctx, node, nums, names)

    def _check_static_call(self, ctx, call, nums, names):
        for i, arg in enumerate(call.args):
            if i in nums and astutil.is_mutable_value(arg):
                yield ctx.finding(
                    self.id, arg,
                    f"unhashable container literal passed in static "
                    f"argument position {i}: jit static args must be "
                    "hashable and every new value recompiles")
        for kw in call.keywords:
            if kw.arg in names and astutil.is_mutable_value(kw.value):
                yield ctx.finding(
                    self.id, kw.value,
                    f"unhashable container literal passed for static "
                    f"argument '{kw.arg}': jit static args must be "
                    "hashable and every new value recompiles")

    def _module_mutables(self, ctx):
        out = set()
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and astutil.is_mutable_value(node.value):
                out.add(node.targets[0].id)
        return out

    # --- python side effects captured by the trace
    def _trace_side_effects(self, ctx, fn, module_mutables):
        parents = astutil.parents_of(ctx)
        shadowed = astutil.assigned_names(fn)
        params = set(astutil.param_names(fn))
        for sub in ast.walk(fn):
            if isinstance(sub, FUNC_DEFS):
                params.update(astutil.param_names(sub))

        def closed_over_mutable(name_node):
            return (isinstance(name_node, ast.Name)
                    and name_node.id in module_mutables
                    and name_node.id not in shadowed)

        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    if isinstance(t, ast.Subscript) \
                            and closed_over_mutable(t.value):
                        yield ctx.finding(
                            self.id, node,
                            f"traced function '{fn.name}' writes into "
                            f"closed-over module-level "
                            f"'{t.value.id}': the mutation happens at "
                            "trace time only and is silently skipped on "
                            "compiled calls")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("append", "extend", "add",
                                           "update", "insert",
                                           "setdefault", "pop", "clear",
                                           "remove") \
                    and closed_over_mutable(node.func.value):
                yield ctx.finding(
                    self.id, node,
                    f"traced function '{fn.name}' mutates closed-over "
                    f"module-level '{node.func.value.id}' via "
                    f".{node.func.attr}(): the mutation happens at trace "
                    "time only and is silently skipped on compiled calls")
            elif isinstance(node, ast.JoinedStr):
                # f-strings under a raise are trace-time validation —
                # formatting there is deliberate and runs once
                if any(isinstance(a, ast.Raise)
                       for a in astutil.ancestors(node, parents)):
                    continue
                for part in node.values:
                    if isinstance(part, ast.FormattedValue) \
                            and isinstance(part.value, ast.Name) \
                            and part.value.id in params:
                        yield ctx.finding(
                            self.id, node,
                            f"f-string in traced function '{fn.name}' "
                            f"formats parameter '{part.value.id}': a "
                            "traced value concretizes (or bakes) at "
                            "trace time — feeding it onward (e.g. into "
                            "static args) recompiles every call")
                        break
