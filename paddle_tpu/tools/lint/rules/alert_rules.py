"""alert-rule-documented: every `AlertRule` id constructed in code is
cataloged in docs/observability.md.

Same contract as metric-name, for the anomaly plane (utils/anomaly.py):
the alert table in the observability doc is the rule registry of
record — an operator paging on `recompile_after_warmup` must be able to
look it up.  Ids are read from the first positional argument (or the
`rule_id=` keyword) of `AlertRule(...)` call sites, with module-level
string constants resolved; dynamically-built ids are out of scope, the
same escape hatch the metric-name rule leaves.
"""
import ast
import re

from ..core import Rule, register
from ..astutil import last_name
from .metric_names import module_consts, registered_names

ID_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def alert_rule_sites(tree):
    """Yield (node, rule_id) for every resolvable AlertRule(...) call."""
    consts = module_consts(tree)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and last_name(node.func) == "AlertRule"):
            continue
        arg = node.args[0] if node.args else None
        if arg is None:
            for kw in node.keywords:
                if kw.arg == "rule_id":
                    arg = kw.value
                    break
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            yield node, arg.value
        elif isinstance(arg, ast.Name) and arg.id in consts:
            yield node, consts[arg.id]


@register
class AlertRuleDocumented(Rule):
    id = "alert-rule-documented"
    rationale = ("the docs/observability.md alert table is the alert "
                 "registry of record; an undocumented rule id pages "
                 "operators with no runbook to look up.")

    def check(self, ctx):
        allow = registered_names(ctx.repo_root)
        for node, rule_id in alert_rule_sites(ctx.tree):
            if not ID_RE.match(rule_id):
                yield ctx.finding(
                    self.id, node,
                    f"alert rule id {rule_id!r} is not snake_case "
                    "([a-z][a-z0-9_]*)")
            elif allow is not None and rule_id not in allow:
                yield ctx.finding(
                    self.id, node,
                    f"alert rule id {rule_id!r} is not documented in "
                    "docs/observability.md")
