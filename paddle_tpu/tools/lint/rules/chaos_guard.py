"""chaos-guard: fault points stay scoped and gated.

The chaos harness (utils/chaos.py) promises its fault points are
zero-cost when disabled. That only holds if every production call to
`chaos.fire(...)` / `chaos.value(...)` sits behind the module's enable
flag — and it is only auditable if the points are visibly the chaos
module's (no `from ...chaos import fire` aliasing the injector into an
innocent-looking local name). This rule enforces both:

  * a `chaos.fire`/`chaos.value` call must be lexically inside an
    `if chaos.enabled():` (or `... and chaos.enabled()` etc.) within
    the same function — the guard and the point stay on one screen;
  * importing the fault-point FUNCTIONS out of the module is flagged:
    import the module, so the guard stays greppable at the call site.

utils/chaos.py itself is exempt (it is the implementation)."""
import ast

from ..core import Rule, register
from .. import astutil
from ..astutil import FUNC_DEFS

POINT_FUNCS = {"fire", "value"}
EXEMPT = ("paddle_tpu/utils/chaos.py",)


def _chaos_aliases(tree):
    """Local names the chaos MODULE is bound to in this file
    (`from ..utils import chaos`, `import paddle_tpu.utils.chaos as x`),
    plus the fault-point functions imported directly (flagged)."""
    modules, direct = set(), []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "chaos":
                    modules.add(alias.asname or alias.name)
                elif (node.module or "").endswith("chaos") \
                        and alias.name in POINT_FUNCS | {"enabled"}:
                    direct.append((node, alias.name))
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith(".chaos") or alias.name == "chaos":
                    modules.add(alias.asname or alias.name.split(".")[0])
    return modules, direct


def _is_enabled_call(node, modules):
    """`chaos.enabled()` (or an alias of the module)."""
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "enabled"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in modules)


def _guarded(call, parents, modules):
    """The call sits under an `if` whose test includes chaos.enabled(),
    within the same function (a guard in a caller is invisible at the
    point of use and rots silently)."""
    for anc in astutil.ancestors(call, parents):
        if isinstance(anc, ast.If):
            for sub in ast.walk(anc.test):
                if _is_enabled_call(sub, modules):
                    return True
        if isinstance(anc, FUNC_DEFS + (ast.Lambda,)):
            return False
    return False


@register
class ChaosGuard(Rule):
    id = "chaos-guard"
    rationale = ("Chaos fault points must be zero-cost when disabled "
                 "and greppable: every chaos.fire()/chaos.value() call "
                 "sits behind `if chaos.enabled():` in the same "
                 "function, and the module is imported whole, never "
                 "its point functions.")

    def check(self, ctx):
        if ctx.rel in EXEMPT:
            return
        modules, direct = _chaos_aliases(ctx.tree)
        for node, name in direct:
            yield ctx.finding(
                self.id, node,
                f"importing '{name}' out of the chaos module hides the "
                "injector behind a bare name; import the module "
                "(`from ..utils import chaos`) so the enable guard "
                "stays visible at the call site")
        if not modules:
            return
        parents = astutil.parents_of(ctx)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in POINT_FUNCS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in modules):
                continue
            if not _guarded(node, parents, modules):
                yield ctx.finding(
                    self.id, node,
                    f"chaos.{node.func.attr}() fault point not guarded "
                    "by `if chaos.enabled():` in the same function — "
                    "the zero-cost-when-disabled contract "
                    "(docs/robustness.md) requires the guard "
                    "at every production call site")
