"""General Python-hygiene rules: mutable-default-arg and
swallowed-exception.

Both patterns have bitten JAX codebases in characteristic ways: a
mutable default shared across calls becomes cross-request state in a
serving loop, and a silent broad `except` hides exactly the non-finite /
device-error signals the flight recorder exists to journal.
"""
import ast

from ..core import Rule, register
from .. import astutil
from ..astutil import FUNC_DEFS, last_name


@register
class MutableDefaultArg(Rule):
    id = "mutable-default-arg"
    rationale = ("A mutable default is created once at def time and "
                 "shared by every call — state leaks across requests/"
                 "steps. Default to None and construct inside.")

    def check(self, ctx):
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, FUNC_DEFS + (ast.Lambda,)):
                continue
            a = fn.args
            pos = list(a.posonlyargs) + list(a.args)
            for param, default in zip(pos[len(pos) - len(a.defaults):],
                                      a.defaults):
                yield from self._check(ctx, fn, param, default)
            for param, default in zip(a.kwonlyargs, a.kw_defaults):
                if default is not None:
                    yield from self._check(ctx, fn, param, default)

    def _check(self, ctx, fn, param, default):
        if astutil.is_mutable_value(default):
            name = getattr(fn, "name", "<lambda>")
            yield ctx.finding(
                self.id, default,
                f"mutable default for parameter '{param.arg}' of "
                f"'{name}' is shared across calls; use None and build "
                "it inside")


BROAD = {"Exception", "BaseException"}


def _is_broad(handler):
    t = handler.type
    if t is None:
        return True                             # bare except:
    if last_name(t) in BROAD:
        return True
    if isinstance(t, ast.Tuple):
        return any(last_name(el) in BROAD for el in t.elts)
    return False


def _handles(body):
    """True when the handler body does SOMETHING with the error: any
    raise, call (log/journal/cleanup), return/yield, or assignment —
    i.e. anything beyond pass/continue/constant-expression filler."""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Raise, ast.Return, ast.Yield,
                                 ast.YieldFrom, ast.Call, ast.Assign,
                                 ast.AugAssign, ast.AnnAssign,
                                 ast.Break)):
                return True
    return False


@register
class SwallowedException(Rule):
    id = "swallowed-exception"
    rationale = ("`except: pass` over a broad type hides the failures "
                 "observability exists to surface (non-finite steps, "
                 "device errors) — narrow the type, journal, or re-raise.")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _handles(node.body):
                # bare `except:` additionally eats KeyboardInterrupt/
                # SystemExit — flag it even when handled, unless the
                # handler re-raises
                if node.type is None and not any(
                        isinstance(n, ast.Raise)
                        for s in node.body for n in ast.walk(s)):
                    yield ctx.finding(
                        self.id, node,
                        "bare 'except:' also catches KeyboardInterrupt/"
                        "SystemExit; catch Exception (or narrower)")
                continue
            what = "bare 'except:'" if node.type is None else \
                f"broad 'except {last_name(node.type) or '...'}'"
            yield ctx.finding(
                self.id, node,
                f"{what} swallows the error silently (no re-raise, log, "
                "journal, or handling); narrow the exception or record "
                "it")
