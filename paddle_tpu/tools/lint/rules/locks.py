"""lock-discipline: module-level shared state must be written under a
lock, in modules that adopted locking.

The telemetry/monitor/flight-recorder/profiler layer and the serving
stack are driven from producer threads (metrics exporter, scheduler
submit(), DataLoader workers); their module-level registries are the
shared state. The contract this rule enforces: once a module declares a
module-level threading.Lock/RLock, EVERY function-scope write to its
module-level mutable containers — and every `global` rebind — happens
inside a `with <lock>:` block. Modules without a module-level lock are
out of scope (they opted out of cross-thread mutation entirely).

Import-time writes (module top level) run single-threaded and are
exempt. Attribute writes on module globals (e.g. `_tl.stack = []` on a
threading.local) are exempt: thread-locals are the sanctioned lock-free
idiom.
"""
import ast

from ..core import Rule, register
from .. import astutil
from ..astutil import FUNC_DEFS, last_name

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}
MUTATOR_METHODS = {"append", "appendleft", "extend", "insert", "add",
                   "update", "pop", "popleft", "popitem", "remove",
                   "discard", "clear", "setdefault"}


def _module_bindings(tree):
    """(mutables, globals_, locks) — module-level simple Name targets."""
    mutables, globals_, locks = set(), set(), set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            globals_.add(name)
            if astutil.is_mutable_value(node.value):
                mutables.add(name)
            if isinstance(node.value, ast.Call) \
                    and last_name(node.value.func) in LOCK_FACTORIES:
                locks.add(name)
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            globals_.add(node.target.id)
            if node.value is not None:
                if astutil.is_mutable_value(node.value):
                    mutables.add(node.target.id)
                if isinstance(node.value, ast.Call) \
                        and last_name(node.value.func) in LOCK_FACTORIES:
                    locks.add(node.target.id)
    return mutables, globals_, locks


def _looks_like_lock(expr, locks):
    """`with <expr>:` guards shared state? Module lock names match
    exactly; anything whose terminal identifier mentions 'lock' or
    'mutex' (self._lock, _install_lock) counts too."""
    if isinstance(expr, ast.Call):
        expr = expr.func    # with lock_factory() / lock.acquire_ctx()
    name = last_name(expr)
    if name is None:
        return False
    return name in locks or "lock" in name.lower() or "mutex" in name.lower()


def _under_lock(node, parents, locks):
    for anc in astutil.ancestors(node, parents):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            if any(_looks_like_lock(item.context_expr, locks)
                   for item in anc.items):
                return True
        if isinstance(anc, FUNC_DEFS):
            return False
    return False


@register
class LockDiscipline(Rule):
    id = "lock-discipline"
    rationale = ("Unlocked writes to module-level shared state race "
                 "against the metrics exporter / producer threads; lost "
                 "updates corrupt counters silently.")

    def check(self, ctx):
        mutables, globals_, locks = _module_bindings(ctx.tree)
        if not locks:
            return
        parents = astutil.parents_of(ctx)
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, FUNC_DEFS):
                continue
            # only report in the def that immediately owns the statement
            # (nested defs are visited on their own)
            yield from self._scan_fn(ctx, fn, parents, mutables,
                                     globals_, locks)

    def _scan_fn(self, ctx, fn, parents, mutables, globals_, locks):
        fn_globals = astutil.global_names(fn)
        shadowed = (set(astutil.param_names(fn))
                    | astutil.assigned_names(fn)) - fn_globals

        def owner(node):
            for anc in astutil.ancestors(node, parents):
                if isinstance(anc, FUNC_DEFS):
                    return anc
            return None

        def is_module_mutable(name_node):
            return (isinstance(name_node, ast.Name)
                    and name_node.id in mutables
                    and name_node.id not in shadowed)

        for node in ast.walk(fn):
            if owner(node) is not fn:
                continue
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    if isinstance(t, ast.Subscript) \
                            and is_module_mutable(t.value) \
                            and not _under_lock(node, parents, locks):
                        yield ctx.finding(
                            self.id, node,
                            f"write to module-level mutable "
                            f"'{t.value.id}' outside a lock (module "
                            "declares one; wrap in `with <lock>:`)")
                    elif isinstance(t, ast.Name) and t.id in fn_globals \
                            and t.id in globals_ and t.id not in locks \
                            and not _under_lock(node, parents, locks):
                        yield ctx.finding(
                            self.id, node,
                            f"module global '{t.id}' rebound outside a "
                            "lock (module declares one; wrap in `with "
                            "<lock>:`)")
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and is_module_mutable(t.value) \
                            and not _under_lock(node, parents, locks):
                        yield ctx.finding(
                            self.id, node,
                            f"del on module-level mutable "
                            f"'{t.value.id}' outside a lock (module "
                            "declares one; wrap in `with <lock>:`)")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATOR_METHODS \
                    and is_module_mutable(node.func.value) \
                    and not _under_lock(node, parents, locks):
                yield ctx.finding(
                    self.id, node,
                    f".{node.func.attr}() on module-level mutable "
                    f"'{node.func.value.id}' outside a lock (module "
                    "declares one; wrap in `with <lock>:`)")
