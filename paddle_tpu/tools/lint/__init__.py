"""ptlint — JAX-aware static analysis for paddle_tpu.

    from paddle_tpu.tools import lint
    findings = lint.lint_paths(["paddle_tpu"], repo_root=".")

Rules (lint.RULES) cover tracer safety (host-sync-in-trace), compile
stability (recompile-hazard), concurrency (lock-discipline), hygiene
(mutable-default-arg, swallowed-exception), the metric-name registry
contract, and fault-point gating (chaos-guard). `scripts/ptlint.py` is the CLI; docs/static_analysis.md is
the rule catalog. Suppress per line with `# ptlint: disable=<rule>`;
grandfather findings in scripts/ptlint_baseline.json (see
lint.baseline).
"""
from .core import (Finding, Rule, RULES, register, lint_file, lint_paths,
                   iter_py_files)
from . import baseline
from . import rules  # noqa: F401  (registers the built-in rules)

__all__ = ["Finding", "Rule", "RULES", "register", "lint_file",
           "lint_paths", "iter_py_files", "baseline"]
