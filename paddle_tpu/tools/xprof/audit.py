"""Compile-level audit: snapshot tracked XLA programs, diff vs baseline.

Three analyses per program, each independently degradable (a jax build
or backend that can't answer one question must not cost us the others —
the snapshot records `null` plus a reason string instead of crashing):

  * cost      — `lower(...).cost_analysis()` flops / bytes-accessed via
                `flight_recorder.normalize_cost_analysis` (HLO-level,
                NO second backend compile; the same numbers TrainStep's
                MFU accounting uses);
  * memory    — `lower(...).compile().memory_analysis()` argument /
                output / temp bytes and the derived peak (alias sizes
                are deliberately NOT recorded — see _memory_entry);
  * hlo       — opcode histogram over the optimized executable text
                (`hlo.op_histogram`): fusion count+kinds, collectives,
                instruction count.

`diff()` compares a snapshot against the committed baseline
(scripts/hlo_baseline.json) under per-metric tolerances — all audited
metrics are lower-is-better, so only increases beyond tolerance are
regressions; shrinkage is reported as a note suggesting a baseline
update. `publish()` exports the same numbers as telemetry gauges
(`xla_program_*{function=...}`) and journals them through the current
flight recorder, so the live system and CI gate read one source.
"""
import json

from ...utils.flight_recorder import normalize_cost_analysis
from . import hlo as hlo_mod

SCHEMA_VERSION = 1

# metric -> (section, field); every one is lower-is-better
METRICS = {
    "flops": ("cost", "flops"),
    "bytes_accessed": ("cost", "bytes_accessed"),
    "peak_bytes": ("memory", "peak_bytes"),
    "fusion_count": ("hlo", "fusion_count"),
    "instruction_count": ("hlo", "instruction_count"),
    "collective_count": ("hlo", "collective_count"),
    "collective_bytes": ("hlo", "collective_bytes_total"),
}

# an increase is a regression when cur > base * (1 + rtol) + atol.
# flops are near-exact per lowering; bytes/memory get slack for layout
# and scheduling noise across XLA minor changes; the count metrics get
# small absolute slack so a one-fusion wobble on a tiny program doesn't
# cry wolf, while a de-optimized hot path (many new ops) still trips.
DEFAULT_TOLERANCES = {
    "flops": {"rtol": 0.02, "atol": 1024},
    "bytes_accessed": {"rtol": 0.10, "atol": 4096},
    "peak_bytes": {"rtol": 0.10, "atol": 4096},
    "fusion_count": {"rtol": 0.25, "atol": 2},
    "instruction_count": {"rtol": 0.25, "atol": 8},
    "collective_count": {"rtol": 0.0, "atol": 0},
    # any extra communicated byte on a banked program is a regression —
    # this is the EQuARX-style budget the quantized-collective follow-on
    # gates against, so it gets no slack by default
    "collective_bytes": {"rtol": 0.0, "atol": 0},
}


# ---------------------------------------------------------------------------
# snapshotting
# ---------------------------------------------------------------------------

def _reason(exc):
    return f"{type(exc).__name__}: {exc}"[:300]


def _memory_entry(compiled):
    ma = compiled.memory_analysis()
    if ma is None:
        raise RuntimeError("memory_analysis() returned None")
    # NOT recorded: alias_size_in_bytes and generated_code_size_in_bytes
    # do not survive persistent-cache serialization (a cache-hit load
    # reports 0 where the fresh compile reported the donation aliasing),
    # and a snapshot must be identical whether the executable was
    # compiled or loaded — the determinism contract of --json/--diff.
    fields = {
        "argument_bytes": "argument_size_in_bytes",
        "output_bytes": "output_size_in_bytes",
        "temp_bytes": "temp_size_in_bytes",
    }
    out = {}
    for key, attr in fields.items():
        v = getattr(ma, attr, None)
        out[key] = int(v) if isinstance(v, (int, float)) else None
    missing = [k for k, v in out.items() if v is None]
    if missing:
        # ALL components or nothing: a peak computed from a partial
        # field set would diff as a huge spurious "improvement" against
        # a complete baseline — degrade to null + reason instead
        raise RuntimeError(
            f"memory stats missing {missing}: {ma!r}")
    # args + outputs + temps: an UPPER BOUND on the executable's HBM
    # high-water mark, not the exact peak — XLA reports a donated
    # buffer's bytes on BOTH the argument and output side, and the
    # aliasing size that would correct it does not survive
    # persistent-cache loads (see the determinism note above), so it is
    # deliberately not subtracted. Consistent run-to-run, which is all
    # the regression gate needs.
    out["peak_bytes"] = sum(out[k] for k in fields)
    return out


def audit_jitted(jitted, *args, **kwargs):
    """Audit one jit-wrapped callable against example (or abstract
    ShapeDtypeStruct) arguments. Returns the per-program entry dict:
    `cost` / `memory` / `hlo` sections (null where the jax build can't
    answer, with the reason under `unavailable`) plus the flat
    `metrics` map the diff consumes."""
    entry = {"cost": None, "memory": None, "hlo": None}
    unavailable = {}
    lowered = compiled = None
    try:
        lowered = jitted.lower(*args, **kwargs)
    except Exception as e:
        unavailable["cost"] = unavailable["memory"] = unavailable["hlo"] = \
            f"lower() failed: {_reason(e)}"
    if lowered is not None:
        try:
            cost = normalize_cost_analysis(lowered.cost_analysis())
            if cost is None:
                raise RuntimeError("cost_analysis() returned nothing "
                                   "normalizable")
            entry["cost"] = cost
        except Exception as e:
            unavailable["cost"] = _reason(e)
        try:
            compiled = lowered.compile()
        except Exception as e:
            unavailable["memory"] = unavailable["hlo"] = \
                f"compile() failed: {_reason(e)}"
    if compiled is not None:
        try:
            entry["memory"] = _memory_entry(compiled)
        except Exception as e:
            unavailable["memory"] = _reason(e)
        try:
            entry["hlo"] = hlo_mod.op_histogram(compiled.as_text())
        except Exception as e:
            unavailable["hlo"] = _reason(e)
    if unavailable:
        entry["unavailable"] = unavailable
    entry["metrics"] = extract_metrics(entry)
    return entry


def extract_metrics(entry):
    out = {}
    for metric, (section, field) in METRICS.items():
        sec = entry.get(section)
        v = sec.get(field) if isinstance(sec, dict) else None
        out[metric] = v if isinstance(v, (int, float)) \
            and not isinstance(v, bool) else None
    return out


def degrade(fn):
    """Deliberately de-optimize a program — the audit's positive
    control (`hlo_audit.py --inject`). Every float input leaf is
    dragged through an extra transcendental reduction whose result
    becomes an additional program output, so DCE cannot remove it and
    an optimization barrier keeps it out of existing fusions: one more
    full HBM pass over the weights and caches, extra instructions and
    fusions — exactly the compile-level fingerprint of a broken hot
    path, which the diff must flag."""
    import jax
    import jax.numpy as jnp

    def degraded(*args, **kwargs):
        out = fn(*args, **kwargs)
        junk = jnp.asarray(0.0, jnp.float32)
        for leaf in jax.tree_util.tree_leaves((args, kwargs)):
            dt = getattr(leaf, "dtype", None)
            if dt is not None and jnp.issubdtype(dt, jnp.floating):
                junk = junk + jnp.sum(
                    jnp.tanh(leaf.astype(jnp.float32) * 1.0001))
        return out, jax.lax.optimization_barrier(junk)

    return degraded


def snapshot_spec(spec, inject=False):
    """Audit one program spec (see registry.tracked_program_specs).
    A spec carries either a prebuilt `jitted` callable or a raw `fn`
    (+ optional `jit_kwargs`); injection needs the raw fn to wrap."""
    import jax
    if inject:
        if spec.get("fn") is None:
            raise ValueError(
                f"program {spec['name']!r} exposes no raw fn to degrade")
        jitted = jax.jit(degrade(spec["fn"]), **spec.get("jit_kwargs", {}))
    elif spec.get("jitted") is not None:
        jitted = spec["jitted"]
    else:
        jitted = jax.jit(spec["fn"], **spec.get("jit_kwargs", {}))
    entry = audit_jitted(jitted, *spec["args"])
    if spec.get("description"):
        entry["description"] = spec["description"]
    if inject:
        entry["injected"] = True
    return entry


def snapshot_programs(specs, inject=()):
    """Audit a list of specs -> snapshot dict. `inject` names programs
    to deliberately de-optimize (test/debug only)."""
    import jax
    inject = set(inject or ())
    unknown = inject - {s["name"] for s in specs}
    if unknown:
        raise ValueError(f"--inject names unknown programs: "
                         f"{sorted(unknown)}")
    programs = {}
    for spec in specs:
        programs[spec["name"]] = snapshot_spec(
            spec, inject=spec["name"] in inject)
    return {
        "schema": SCHEMA_VERSION,
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "programs": programs,
    }


# ---------------------------------------------------------------------------
# baseline + diff
# ---------------------------------------------------------------------------

def make_baseline(snapshot, previous=None, keep_missing=False):
    """Compact a snapshot into the committed baseline shape: per program
    the flat metric values (nulls preserved — unavailable analyses are
    a recorded fact, and null-vs-null diffs clean) plus tolerances.
    Per-program tolerance overrides hand-edited into a previous baseline
    survive the update. `keep_missing=True` (a --programs SUBSET update)
    carries previous-baseline programs absent from this snapshot over
    unchanged, so re-banking one program never silently un-tracks the
    others; a FULL update drops them (deliberate removal). A subset
    merge across backends is refused — it would stamp cpu-banked
    numbers with a tpu backend (or vice versa) and license exactly the
    cross-backend comparison the backend stamp exists to prevent."""
    if keep_missing and previous is not None \
            and previous.get("backend") != snapshot["backend"]:
        raise ValueError(
            f"refusing a --programs subset baseline update across "
            f"backends: baseline is {previous.get('backend')!r}, this "
            f"snapshot is {snapshot['backend']!r} — re-bank ALL "
            "programs on one backend instead")
    prev_programs = (previous or {}).get("programs", {})
    programs = {}
    if keep_missing:
        programs.update({k: v for k, v in prev_programs.items()
                         if k not in snapshot["programs"]})
    for name, entry in sorted(snapshot["programs"].items()):
        row = {"metrics": dict(entry["metrics"])}
        hlo_sec = entry.get("hlo")
        if isinstance(hlo_sec, dict) and "collectives" in hlo_sec:
            # per-opcode {count, bytes} rows — the collective-budget rule
            # (tools/jxaudit/mesh_rules.py) gates sharded programs against
            # these, so an accidental all-gather is named, not just a +1
            # in collective_count. An empty dict is meaningful: it banks
            # a ZERO budget for every collective opcode.
            cb = hlo_sec.get("collective_bytes") or {}
            row["collectives"] = {
                op: {"count": n, "bytes": cb.get(op)}
                for op, n in sorted(hlo_sec["collectives"].items())}
        if entry.get("unavailable"):
            row["unavailable"] = dict(entry["unavailable"])
        old_tol = prev_programs.get(name, {}).get("tolerances")
        if old_tol:
            row["tolerances"] = old_tol
        programs[name] = row
    return {
        "version": SCHEMA_VERSION,
        "backend": snapshot["backend"],
        "jax_version": snapshot["jax_version"],
        "tolerances": (previous or {}).get("tolerances",
                                           DEFAULT_TOLERANCES),
        "programs": programs,
    }


def load_baseline(path):
    with open(path) as f:
        return json.load(f)


def save_baseline(baseline, path):
    with open(path, "w") as f:
        json.dump(baseline, f, indent=1, sort_keys=True)
        f.write("\n")


def _limit(base, tol):
    return base * (1.0 + tol.get("rtol", 0.0)) + tol.get("atol", 0.0)


def diff(snapshot, baseline):
    """Compare a snapshot against the baseline. Returns
    (findings, notes): findings are tolerance-exceeding INCREASES of a
    lower-is-better metric (the CI gate — exit 1); notes are
    non-gating observations (backend mismatch, programs or analyses
    appearing/disappearing, improvements worth a baseline update)."""
    findings, notes = [], []
    if snapshot.get("backend") != baseline.get("backend"):
        notes.append(
            f"backend mismatch: snapshot={snapshot.get('backend')!r} "
            f"baseline={baseline.get('backend')!r} — compiled programs "
            "are not comparable across backends; skipping the diff "
            "(re-baseline on this backend to gate here)")
        return findings, notes
    base_tol = baseline.get("tolerances", DEFAULT_TOLERANCES)
    base_programs = baseline.get("programs", {})
    cur_programs = snapshot.get("programs", {})
    for name in sorted(set(base_programs) - set(cur_programs)):
        findings.append({
            "program": name, "metric": "-", "base": None, "current": None,
            "limit": None,
            "why": "tracked program missing from the snapshot (renamed or "
                   "dropped? update scripts/hlo_baseline.json "
                   "deliberately via --update-baseline)"})
    for name in sorted(set(cur_programs) - set(base_programs)):
        notes.append(f"{name}: not in baseline — run --update-baseline "
                     "to start tracking it")
    for name in sorted(set(cur_programs) & set(base_programs)):
        cur = cur_programs[name].get("metrics", {})
        brow = base_programs[name]
        base = brow.get("metrics", {})
        tols = dict(base_tol)
        tols.update(brow.get("tolerances", {}))
        for metric in METRICS:
            b, c = base.get(metric), cur.get(metric)
            if b is None and c is None:
                continue        # unavailable on both sides: clean
            if c is None:
                notes.append(
                    f"{name}.{metric}: analysis unavailable here but "
                    f"baselined at {b:g} — capability lost on this jax "
                    "build (not gating)")
                continue
            if b is None:
                notes.append(
                    f"{name}.{metric}: now measurable ({c:g}) but null "
                    "in baseline — run --update-baseline to gate it")
                continue
            tol = tols.get(metric, {})
            limit = _limit(b, tol)
            if c > limit:
                findings.append({
                    "program": name, "metric": metric, "base": b,
                    "current": c, "limit": limit,
                    "why": f"{metric} regressed {b:g} -> {c:g} "
                           f"(tolerance ceiling {limit:g})"})
            elif b - (c * (1.0 + tol.get("rtol", 0.0))
                      + tol.get("atol", 0.0)) > 0:
                notes.append(
                    f"{name}.{metric}: improved {b:g} -> {c:g} — "
                    "consider --update-baseline to lock in the win")
    return findings, notes


def render_findings(findings, notes):
    lines = []
    for f in findings:
        lines.append(f"REGRESSION {f['program']}.{f['metric']}: {f['why']}")
    for n in notes:
        lines.append(f"note: {n}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# live export (telemetry gauges + flight-recorder journal)
# ---------------------------------------------------------------------------

def publish(snapshot, recorder=None):
    """Export a snapshot's per-program numbers as telemetry gauges and
    journal them through `recorder` (default: the current flight
    recorder, if any) — the audit's measurements become part of the
    same live surface the compile events already ride."""
    from ...utils import telemetry, flight_recorder as fr
    gauges = {
        "flops": telemetry.gauge(
            "xla_program_flops",
            "FLOPs per tracked compiled program (HLO cost analysis)",
            labelnames=("function",)),
        "bytes_accessed": telemetry.gauge(
            "xla_program_bytes",
            "Bytes accessed per tracked compiled program",
            labelnames=("function",)),
        "peak_bytes": telemetry.gauge(
            "xla_program_peak_memory_bytes",
            "Peak executable memory (args+outputs+temps) per tracked "
            "program", labelnames=("function",)),
        "fusion_count": telemetry.gauge(
            "xla_program_fusion_count",
            "Fusion instructions in the optimized HLO per tracked "
            "program", labelnames=("function",)),
    }
    rec = recorder if recorder is not None else fr.get_recorder()
    for name, entry in sorted(snapshot.get("programs", {}).items()):
        m = entry.get("metrics", {})
        for metric, gauge in gauges.items():
            if m.get(metric) is not None:
                gauge.labels(name).set(m[metric])
        if rec is not None:
            rec.xla_program(
                name, flops=m.get("flops"),
                bytes_accessed=m.get("bytes_accessed"),
                peak_memory_bytes=m.get("peak_bytes"),
                fusion_count=m.get("fusion_count"))


def rollup(snapshot):
    """Compact per-program {flops, bytes_accessed, fusion_count,
    peak_bytes} map for bench JSON embedding."""
    out = {}
    for name, entry in sorted(snapshot.get("programs", {}).items()):
        m = entry.get("metrics", {})
        out[name] = {k: m.get(k) for k in
                     ("flops", "bytes_accessed", "fusion_count",
                      "peak_bytes")}
    return out
