"""Tracked-program registry: the compiled programs the repo gates on.

Each entry is a deterministic abstract-shape lowering spec — a tiny
canonical configuration (2 layers, hidden <= 128: HLO structure, not
capacity, is what's audited, and tier-1 shares the 870s budget) of a
REAL hot path:

  * `serving_decode_wave` / `serving_prefill` — the ServingEngine's two
    programs, lowered from the engine's own raw closures (the engine
    stashes them precisely so this audit and the serving path cannot
    drift apart);
  * `paged_decode_wave` / `paged_prefill_chunk` — the
    PagedServingEngine's two programs (block-table KV cache, chunked
    prefill; serving/paged), same stashed-closure discipline — jxaudit
    verifies the block POOL leaves stay donation-aliased at engine
    shapes;
  * `paged_decode_attention` — the block-table decode core
    (scatter/gather through traced tables + the GQA cached core) — the
    reference oracle the fused kernels are measured against;
  * `paged_fused_decode_attention` / `paged_fused_chunk_attention` —
    the fused paged-attention cores (nn/paged_attention.py): the same
    scatter + attend, but reading K/V straight out of the pool through
    the table with an online softmax — no gathered
    [B, Hkv, nblk*BS, D] intermediate. Audited with the dispatch's
    backend-auto kernel (lax on CPU — the implementation the banked
    CPU baselines gate; pallas on TPU);
  * `train_step` — `jit.TrainStep` (forward + backward + AdamW, donated
    state) on the canonical 2-layer GPT config — the same topology
    bench.py's CPU smoke compiles, so the persistent compile cache is
    shared;
  * `sharded_train_step` — `distributed.sharded.ShardedTrainStep`
    (GSPMD, ZeRO-1 dp-sharded optimizer state) on the same 2-layer GPT
    config over the tier-1 8-CPU-device dp mesh, active dropout so the
    PRNG key stays a live entry parameter — jxaudit's donation rule
    verifies the dp-SHARDED opt-state leaves are actually aliased in
    the partitioned HLO (the PR-7 eager-optimizer donation bug, sharded
    incarnation); `sharded_train_step_z3` is the same step at ZeRO-3
    (params dp-sharded too, gather-on-use);
  * `sharded_decode_wave` — the dense engine's decode wave pjit'd with
    head-sharded K/V caches over the 8-device `mp` mesh (the ROADMAP
    item-1 tensor-parallel serving scaffold, gated by the mesh-aware
    audit before the real TP engine lands);
  * `cached_decode_attention` — the GQA single-token cached attention
    core from nn/transformer.py with a per-slot position VECTOR (the
    serving decode regime);
  * `prefill_flash_attention` — the causal prompt-phase attention array
    kernel the prefill paths route through.

Specs are dicts: {name, fn | jitted, args, jit_kwargs, description}.
Builders reset the global seed so repeated snapshots are
bit-deterministic; parameter VALUES never reach the lowering anyway —
only shapes/dtypes do.
"""

# serving canonical shape (mirrors tests/test_serving.py scale)
SERVING = dict(vocab=128, hidden=64, layers=2, heads=4, max_len=64,
               prefill_len=16, num_slots=4)
# paged-serving canonical shape (mirrors tests/test_serving_paged.py):
# same model topology, block-table cache
PAGED = dict(vocab=128, hidden=64, layers=2, heads=4, max_len=64,
             block_size=8, num_blocks=33, chunk_len=16, num_slots=4)
# speculative canonical shape (mirrors tests/test_serving_spec.py):
# the PAGED target plus a 1-layer draft GPT and k=3
SPEC = dict(PAGED, spec_k=3, draft_hidden=32, draft_layers=1,
            draft_heads=2)
# train canonical shape == bench.py CPU-smoke config
TRAIN = dict(vocab=512, hidden=128, layers=2, heads=4, seq=128, batch=2)
# sharded-train canonical mesh: the tier-1 8-CPU-device dp mesh
# (conftest's --xla_force_host_platform_device_count=8), ZeRO-1. The
# batch (2) is not divisible by dp, so it rides replicated — the
# exact-reshard regime chaos_train proves bitwise. The z3 sibling
# (`sharded_train_step_z3`) dp-shards the PARAMETERS too — gather-on-use
# in the partitioned HLO, the regime test_zero3.py proves — so the
# mesh-aware audit gates both ZeRO points the repo ships.
SHARDED_TRAIN = dict(TRAIN, dp=8, zero_stage=1, dropout=0.1)
# tensor-parallel decode canonical shape: the dense serving wave with
# heads == mp so the per-slot K/V caches shard cleanly head-wise over
# the tier-1 8-device mesh — the ROADMAP item-1 (TP sharded serving)
# scaffold the mesh-aware audit gates before the real engine lands
SHARDED_SERVING = dict(SERVING, heads=8, mp=8)

TRACKED_PROGRAMS = ("serving_decode_wave", "serving_prefill",
                    "paged_decode_wave", "paged_prefill_chunk",
                    "paged_spec_draft_wave", "paged_spec_verify",
                    "train_step", "sharded_train_step",
                    "sharded_train_step_z3", "sharded_decode_wave",
                    "cached_decode_attention",
                    "paged_decode_attention",
                    "paged_fused_decode_attention",
                    "paged_fused_chunk_attention",
                    "prefill_flash_attention")


def program_cost(spec):
    """Lowering-level cost of ONE tracked-program invocation:
    {"flops", "bytes_accessed", ...} via the HLO cost analysis (no
    second backend compile), or None when this jax build can't answer.
    These are the exact numbers `scripts/hlo_baseline.json` banks per
    program, which is what lets the serving roofline gauges
    (`serving_mfu` / `serving_hbm_util`) be checked against the
    committed baseline."""
    import jax

    from paddle_tpu.utils import flight_recorder

    jitted = spec.get("jitted")
    if jitted is None:
        jitted = jax.jit(spec["fn"], **spec.get("jit_kwargs", {}))
    return flight_recorder.cost_analysis(jitted, *spec["args"])


def engine_program_specs(engine, prefix=None):
    """Audit specs for a LIVE engine's compiled programs, with the
    engine's actual shapes — used on the canonical engines below and by
    bench_serving.py on the engine it just measured. Dispatches on the
    engine flavour: a paged engine (block_pool) audits its
    decode-wave-with-tables and prefill-chunk programs; a speculative
    engine (draft_model) audits its draft/verify/prefill trio."""
    if hasattr(engine, "draft_model"):
        return _spec_engine_specs(engine, prefix or "paged_spec")
    if hasattr(engine, "block_pool"):
        return _paged_engine_specs(engine, prefix or "paged")
    return _dense_engine_specs(engine, prefix or "serving")


def _sampling_vec_args(engine):
    """The shared sampling-scenario vectors every wave program takes
    (sample flag, temperature, top-k, top-p, [S, V] bias/mask) — the
    audit specs mirror engine._sampling_args so signatures can't
    drift."""
    import jax.numpy as jnp
    S = engine.num_slots
    return (jnp.zeros((S,), bool), jnp.ones((S,), jnp.float32),
            jnp.zeros((S,), jnp.int32), jnp.ones((S,), jnp.float32),
            jnp.zeros((S, engine.vocab_size), jnp.float32))


def _prefill_sampling_args(engine):
    """The prefill programs' per-request sampling scalars + bias row."""
    import jax.numpy as jnp
    return (jnp.asarray(False), jnp.float32(1.0), jnp.int32(0),
            jnp.float32(1.0),
            jnp.zeros((engine.vocab_size,), jnp.float32))


def _dense_engine_specs(engine, prefix):
    import jax
    import jax.numpy as jnp
    import numpy as np

    S = engine.num_slots
    key = jax.random.PRNGKey(0)
    jit_kwargs = {"donate_argnums": engine._program_donate_argnums}
    decode_args = (
        engine._params, engine._buffers, engine._caches,
        jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
        jnp.ones((S,), bool), *_sampling_vec_args(engine),
        jnp.zeros((S,), bool),          # poison (chaos NaN injection)
        key)
    prefill_args = (
        engine._params, engine._buffers, engine._caches,
        jnp.asarray(np.zeros((engine.prefill_len,), np.int32)),
        jnp.int32(1), jnp.int32(0), *_prefill_sampling_args(engine),
        key)
    return [
        {"name": f"{prefix}_decode_wave", "fn": engine._decode_wave_fn,
         "args": decode_args, "jit_kwargs": jit_kwargs,
         "description": f"one batched decode token for every slot "
                        f"(slots={S}, max_len={engine.max_len})"},
        {"name": f"{prefix}_prefill", "fn": engine._prefill_fn,
         "args": prefill_args, "jit_kwargs": jit_kwargs,
         "description": f"one prompt bucket admission "
                        f"(prefill_len={engine.prefill_len})"},
    ]


def _paged_engine_specs(engine, prefix):
    import jax
    import jax.numpy as jnp
    import numpy as np

    S, nblk = engine.num_slots, engine.blocks_per_slot
    C = engine.prefill_chunk_len
    key = jax.random.PRNGKey(0)
    jit_kwargs = {"donate_argnums": engine._program_donate_argnums}
    decode_args = (
        engine._params, engine._buffers, engine._caches,
        jnp.zeros((S, nblk), jnp.int32),     # block tables (traced!)
        jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
        jnp.ones((S,), bool), *_sampling_vec_args(engine),
        jnp.zeros((S,), bool),               # poison
        key)
    prefill_args = (
        engine._params, engine._buffers, engine._caches,
        jnp.zeros((nblk,), jnp.int32),       # one slot's table row
        jnp.asarray(np.zeros((C,), np.int32)),
        jnp.int32(0), jnp.int32(1), jnp.int32(0),
        *_prefill_sampling_args(engine), key)
    return [
        {"name": f"{prefix}_decode_wave", "fn": engine._decode_wave_fn,
         "args": decode_args, "jit_kwargs": jit_kwargs,
         "description": f"one batched decode token for every slot "
                        f"through block tables (slots={S}, "
                        f"blocks={engine.block_pool.num_blocks}x"
                        f"{engine.block_size})"},
        {"name": f"{prefix}_prefill_chunk", "fn": engine._prefill_fn,
         "args": prefill_args, "jit_kwargs": jit_kwargs,
         "description": f"one prompt chunk admission through a block "
                        f"table (chunk={C})"},
    ]


def _spec_engine_specs(engine, prefix):
    """Audit specs for a LIVE SpeculativePagedEngine's three programs:
    the draft wave (k+1 draft decode steps in one executable), the
    verify wave (chunk-scored target forward + exact acceptance-
    rejection tail), and the dual-model prefill chunk. jxaudit's
    donation rule runs over these to prove BOTH the target and draft
    KV-pool leaves stay aliased; hlo_audit banks the verify program's
    bytes-accessed so a k+1-disproportionate regression gates."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    S, nblk, k = engine.num_slots, engine.blocks_per_slot, engine.spec_k
    C = engine.prefill_chunk_len
    V = engine.vocab_size
    key = jax.random.PRNGKey(0)
    jit_kwargs = {"donate_argnums": engine._program_donate_argnums}
    tables = jnp.zeros((S, nblk), jnp.int32)        # traced tables
    tok = jnp.zeros((S,), jnp.int32)
    pos = jnp.zeros((S,), jnp.int32)
    spec_len = jnp.ones((S,), jnp.int32)
    # the draft wave has no active mask (inactive lanes ride scratch
    # table rows; the verify tail discards their proposals)
    draft_args = (engine._draft_params, engine._draft_buffers,
                  engine._caches, tables, tok, pos,
                  *_sampling_vec_args(engine), spec_len, key)
    verify_args = (
        engine._params, engine._buffers, engine._caches, tables, tok,
        pos, jnp.ones((S,), bool), *_sampling_vec_args(engine), spec_len,
        jnp.zeros((S, k), jnp.int32),               # draft tokens
        jnp.zeros((S, k, V), jnp.float32),          # draft probs
        jnp.zeros((S,), bool),                      # poison
        key)
    prefill_args = (
        engine._params, engine._buffers, engine._caches,
        engine._draft_params, engine._draft_buffers,
        jnp.zeros((nblk,), jnp.int32),
        jnp.asarray(np.zeros((C,), np.int32)),
        jnp.int32(0), jnp.int32(1), jnp.int32(0),
        *_prefill_sampling_args(engine), key)
    return [
        {"name": f"{prefix}_draft_wave", "fn": engine._draft_wave_fn,
         "args": draft_args, "jit_kwargs": jit_kwargs,
         "description": f"k+1={engine.spec_k + 1} draft decode steps "
                        f"in one executable (slots={S})"},
        {"name": f"{prefix}_verify", "fn": engine._decode_wave_fn,
         "args": verify_args, "jit_kwargs": jit_kwargs,
         "description": f"verify-once: one chunk-scored target forward "
                        f"over C=k+1={engine.spec_k + 1} positions + "
                        "exact acceptance-rejection"},
        {"name": f"{prefix}_prefill_chunk", "fn": engine._prefill_fn,
         "args": prefill_args, "jit_kwargs": jit_kwargs,
         "description": f"dual-model prompt chunk admission (target + "
                        f"draft K/V, chunk={C})"},
    ]


def _serving_specs():
    import paddle_tpu as pt
    from paddle_tpu.nlp import GPTConfig, GPTForPretraining
    from paddle_tpu.serving import ServingEngine

    pt.seed(0)
    cfg = GPTConfig(vocab_size=SERVING["vocab"],
                    hidden_size=SERVING["hidden"],
                    num_layers=SERVING["layers"],
                    num_heads=SERVING["heads"],
                    max_seq_len=SERVING["max_len"],
                    dropout=0.0, attn_dropout=0.0)
    engine = ServingEngine(GPTForPretraining(cfg),
                           num_slots=SERVING["num_slots"],
                           max_len=SERVING["max_len"],
                           prefill_len=SERVING["prefill_len"])
    return engine_program_specs(engine)


def _paged_serving_specs():
    import paddle_tpu as pt
    from paddle_tpu.nlp import GPTConfig, GPTForPretraining
    from paddle_tpu.serving import PagedServingEngine

    pt.seed(0)
    cfg = GPTConfig(vocab_size=PAGED["vocab"],
                    hidden_size=PAGED["hidden"],
                    num_layers=PAGED["layers"],
                    num_heads=PAGED["heads"],
                    max_seq_len=PAGED["max_len"],
                    dropout=0.0, attn_dropout=0.0)
    engine = PagedServingEngine(GPTForPretraining(cfg),
                                num_slots=PAGED["num_slots"],
                                max_len=PAGED["max_len"],
                                block_size=PAGED["block_size"],
                                num_blocks=PAGED["num_blocks"],
                                prefill_chunk_len=PAGED["chunk_len"])
    return engine_program_specs(engine)


def _spec_serving_specs():
    import paddle_tpu as pt
    from paddle_tpu.nlp import GPTConfig, GPTForPretraining
    from paddle_tpu.serving import SpeculativePagedEngine

    C = SPEC
    pt.seed(0)
    cfg = GPTConfig(vocab_size=C["vocab"], hidden_size=C["hidden"],
                    num_layers=C["layers"], num_heads=C["heads"],
                    max_seq_len=C["max_len"], dropout=0.0,
                    attn_dropout=0.0)
    model = GPTForPretraining(cfg)
    dcfg = GPTConfig(vocab_size=C["vocab"],
                     hidden_size=C["draft_hidden"],
                     num_layers=C["draft_layers"],
                     num_heads=C["draft_heads"],
                     max_seq_len=C["max_len"], dropout=0.0,
                     attn_dropout=0.0)
    engine = SpeculativePagedEngine(model, GPTForPretraining(dcfg),
                                    spec_k=C["spec_k"],
                                    num_slots=C["num_slots"],
                                    max_len=C["max_len"],
                                    block_size=C["block_size"],
                                    num_blocks=C["num_blocks"],
                                    prefill_chunk_len=C["chunk_len"])
    return engine_program_specs(engine)


def train_step_spec(step, inputs, labels):
    """Audit spec for a LIVE TrainStep: lowers the step's own compiled
    callable with its current state (injection needs a raw fn, which
    TrainStep does not expose — gate regressions via the registry's
    canonical instance instead)."""
    import jax
    import jax.numpy as jnp
    args = (step.params, step.buffers, step.opt_state, step.grad_acc,
            jax.random.PRNGKey(0), jnp.asarray(1e-4, jnp.float32),
            jnp.asarray(1, jnp.int32), tuple(inputs), tuple(labels))
    return {"name": "train_step", "jitted": step._compiled, "args": args,
            # donation metadata for the semantic audit (tools/jxaudit):
            # a prebuilt jitted carries no introspectable donate info,
            # so the spec passes the TrainStep's own declaration through
            "donate_argnums": getattr(step, "_donate_argnums", ()),
            "arg_names": ("params", "buffers", "opt_state", "acc", "key",
                          "lr", "step_i", "inputs", "labels"),
            "description": "forward+backward+optimizer, one donated "
                           "executable (canonical 2-layer GPT)"}


def _train_step_spec():
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nlp import GPTConfig, GPTForPretraining
    from paddle_tpu.nlp.gpt import gpt_pretrain_loss

    pt.seed(0)
    cfg = GPTConfig(vocab_size=TRAIN["vocab"], hidden_size=TRAIN["hidden"],
                    num_layers=TRAIN["layers"], num_heads=TRAIN["heads"],
                    max_seq_len=TRAIN["seq"], dropout=0.0,
                    attn_dropout=0.0)
    model = GPTForPretraining(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters())
    step = TrainStep(model, gpt_pretrain_loss, opt, donate=True)
    ids = np.zeros((TRAIN["batch"], TRAIN["seq"]), np.int32)
    return train_step_spec(step, (ids,), (ids,))


def sharded_train_step_spec(step, inputs, labels,
                            name="sharded_train_step"):
    """Audit spec for a LIVE ShardedTrainStep: lowers the step's own
    compiled (pjit'd, in/out-sharded, donated) callable with its
    current sharded state — the program a mesh training run actually
    dispatches. `inputs`/`labels` are global batch arrays; they ride
    through the step's own `_shard_batch` so the lowering sees the same
    placements a real step does. The `sharding` entry is the step's own
    declaration of record (`audit_sharding_decl`) — what the mesh-aware
    rules (tools/jxaudit/mesh_rules.py) compare against the compiled
    module's committed annotations."""
    import jax
    import jax.numpy as jnp
    args = (step.params, step.buffers, step.opt_state, step.grad_acc,
            jax.random.PRNGKey(0), jnp.asarray(1e-4, jnp.float32),
            jnp.asarray(1, jnp.int32), step._shard_batch(tuple(inputs)),
            step._shard_batch(tuple(labels)))
    return {"name": name, "jitted": step._compiled,
            "args": args,
            "donate_argnums": getattr(step, "_donate_argnums", ()),
            "arg_names": ("params", "buffers", "opt_state", "acc", "key",
                          "lr", "step_i", "inputs", "labels"),
            "sharding": step.audit_sharding_decl(),
            "description": "GSPMD forward+backward+AdamW with ZeRO "
                           f"stage-{step.zero_stage} dp-sharded opt "
                           "state, one donated executable "
                           f"(mesh {dict(zip(step.mesh.axis_names, step.mesh.devices.shape))})"}


def _sharded_train_step_spec(zero_stage=None,
                             name="sharded_train_step"):
    import numpy as np
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as pt
    from paddle_tpu.distributed.sharded import ShardedTrainStep
    from paddle_tpu.nlp import GPTConfig, GPTForPretraining
    from paddle_tpu.nlp.gpt import gpt_pretrain_loss

    C = SHARDED_TRAIN
    if zero_stage is None:
        zero_stage = C["zero_stage"]
    pt.seed(0)
    cfg = GPTConfig(vocab_size=C["vocab"], hidden_size=C["hidden"],
                    num_layers=C["layers"], num_heads=C["heads"],
                    max_seq_len=C["seq"], dropout=C["dropout"],
                    attn_dropout=0.0)
    model = GPTForPretraining(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters())
    # an explicit mesh, NOT make_mesh: building an audit spec must not
    # install (or leak) global mesh state into whatever runs next
    devs = jax.devices()
    dp = min(C["dp"], len(devs))
    mesh = Mesh(np.asarray(devs[:dp]).reshape(dp), ("dp",))
    step = ShardedTrainStep(model, gpt_pretrain_loss, opt, mesh=mesh,
                            zero_stage=zero_stage)
    ids = np.zeros((C["batch"], C["seq"]), np.int32)
    return sharded_train_step_spec(step, (ids,), (ids,), name=name)


def _sharded_decode_wave_spec():
    """pjit'd tensor-parallel decode wave on the 8-device CPU mesh: the
    dense engine's OWN decode-wave closure, re-jitted with the per-slot
    K/V caches sharded head-wise (`P(None, 'mp', None, None)`) and
    params/buffers replicated — a faithful scaffold of ROADMAP item 1's
    TP serving regime, with the caches still donated so the mesh-aware
    donation rule proves aliasing survives pjit at shard shapes."""
    import numpy as np
    import jax
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as pt
    from paddle_tpu.nlp import GPTConfig, GPTForPretraining
    from paddle_tpu.serving import ServingEngine

    C = SHARDED_SERVING
    pt.seed(0)
    cfg = GPTConfig(vocab_size=C["vocab"], hidden_size=C["hidden"],
                    num_layers=C["layers"], num_heads=C["heads"],
                    max_seq_len=C["max_len"], dropout=0.0,
                    attn_dropout=0.0)
    engine = ServingEngine(GPTForPretraining(cfg),
                           num_slots=C["num_slots"],
                           max_len=C["max_len"],
                           prefill_len=C["prefill_len"])
    devs = jax.devices()
    mp = min(C["mp"], len(devs))
    mesh = Mesh(np.asarray(devs[:mp]).reshape(mp), ("mp",))
    ns = lambda spec: NamedSharding(mesh, spec)
    cache_spec = P(None, "mp", None, None)    # [slots, HEADS, len, d]
    base = _dense_engine_specs(engine, "sharded")[0]
    # caches are argnum 2 (the donated state); everything else —
    # params, buffers, per-slot control vectors, key — is replicated.
    # in_shardings rides pytree PREFIXES: one NamedSharding per argnum
    # covers every leaf of that arg.
    in_sh = tuple(ns(cache_spec) if i == 2 else ns(P())
                  for i in range(len(base["args"])))
    args = tuple(jax.device_put(a, sh)
                 for a, sh in zip(base["args"], in_sh))
    return {
        "name": "sharded_decode_wave", "fn": base["fn"], "args": args,
        "jit_kwargs": dict(base["jit_kwargs"], in_shardings=in_sh),
        "donate_argnums": base["jit_kwargs"]["donate_argnums"],
        "sharding": {
            "mesh_axes": {a: int(mesh.shape[a])
                          for a in mesh.axis_names},
            "in_specs": {2: cache_spec},
            "constraint_specs": [],
            "expected_collectives": (),
        },
        "description": "tensor-parallel batched decode token: head-"
                       f"sharded K/V caches over mp={mp} "
                       f"(slots={C['num_slots']}, heads={C['heads']})"}


def _attention_specs():
    import jax.numpy as jnp
    from paddle_tpu.nn.paged_attention import (paged_chunk_attention,
                                               paged_decode_attention)
    from paddle_tpu.nn.transformer import (cached_decode_attention,
                                           gather_block_kv,
                                           scatter_block_kv_at,
                                           scatter_block_kv_chunk_batched)
    from paddle_tpu.ops.pallas.flash_attention import _flash_array

    b, h, hkv, L, d = 4, 4, 2, 64, 16
    bs, nblk, num_blocks = 8, 8, 17        # nblk * bs == L
    C = SPEC["spec_k"] + 1                 # the verify chunk width

    def decode_attn(q, ck, cv, pos):
        return cached_decode_attention(q, ck, cv, pos,
                                       scale=1.0 / (d ** 0.5))

    decode_args = (jnp.zeros((b, h, 1, d), jnp.float32),
                   jnp.zeros((b, hkv, L, d), jnp.float32),
                   jnp.zeros((b, hkv, L, d), jnp.float32),
                   jnp.zeros((b,), jnp.int32))

    def paged_decode_attn(q, kv_t, pk, pv, tables, pos):
        # the serving paged decode core: scatter the step's K/V through
        # the tables, attend over the gathered per-row views; the
        # updated pools ride out (donated in-place, like the engine's)
        pk = scatter_block_kv_at(pk, kv_t, tables, pos)
        pv = scatter_block_kv_at(pv, kv_t, tables, pos)
        out = cached_decode_attention(
            q, gather_block_kv(pk, tables), gather_block_kv(pv, tables),
            pos, scale=1.0 / (d ** 0.5))
        return out, pk, pv

    paged_args = (jnp.zeros((b, h, 1, d), jnp.float32),
                  jnp.zeros((b, hkv, 1, d), jnp.float32),
                  jnp.zeros((num_blocks, hkv, bs, d), jnp.float32),
                  jnp.zeros((num_blocks, hkv, bs, d), jnp.float32),
                  jnp.zeros((b, nblk), jnp.int32),
                  jnp.zeros((b,), jnp.int32))

    def fused_decode_attn(q, kv_t, pk, pv, tables, pos):
        # the fused sibling of paged_decode_attn: same scatter, but the
        # attend reads the pool through the table (online softmax) —
        # the [B, Hkv, nblk*BS, D] gathered view never materialises.
        # kernel=None: the dispatch's backend auto-selection, i.e. the
        # implementation the serving engines actually compile here
        pk = scatter_block_kv_at(pk, kv_t, tables, pos)
        pv = scatter_block_kv_at(pv, kv_t, tables, pos)
        out = paged_decode_attention(q, pk, pv, tables, pos,
                                     scale=1.0 / (d ** 0.5))
        return out, pk, pv

    def fused_chunk_attn(q, kv_c, pk, pv, tables, start, valid_len):
        # the chunked form (spec verify / prefill chunk): C queries per
        # lane at per-lane offsets, batched scatter + fused attend
        pk = scatter_block_kv_chunk_batched(pk, kv_c, tables, start,
                                            valid_len)
        pv = scatter_block_kv_chunk_batched(pv, kv_c, tables, start,
                                            valid_len)
        out = paged_chunk_attention(q, pk, pv, tables, start,
                                    scale=1.0 / (d ** 0.5))
        return out, pk, pv

    fused_chunk_args = (jnp.zeros((b, h, C, d), jnp.float32),
                        jnp.zeros((b, hkv, C, d), jnp.float32),
                        jnp.zeros((num_blocks, hkv, bs, d), jnp.float32),
                        jnp.zeros((num_blocks, hkv, bs, d), jnp.float32),
                        jnp.zeros((b, nblk), jnp.int32),
                        jnp.zeros((b,), jnp.int32),
                        jnp.full((b,), C, jnp.int32))

    def prefill_attn(q, k, v):
        return _flash_array(q, k, v, causal=True)

    prefill_args = (jnp.zeros((2, h, L, d), jnp.float32),
                    jnp.zeros((2, h, L, d), jnp.float32),
                    jnp.zeros((2, h, L, d), jnp.float32))
    return [
        {"name": "cached_decode_attention", "fn": decode_attn,
         "args": decode_args,
         "description": "GQA cached decode attention core, per-slot "
                        "position vector"},
        {"name": "paged_decode_attention", "fn": paged_decode_attn,
         "args": paged_args,
         "jit_kwargs": {"donate_argnums": (2, 3)},
         "description": "block-table decode attention core: KV "
                        "scatter/gather through traced tables + the "
                        "GQA cached core (the fused kernels' reference "
                        "oracle)"},
        {"name": "paged_fused_decode_attention", "fn": fused_decode_attn,
         "args": paged_args,
         "jit_kwargs": {"donate_argnums": (2, 3)},
         "description": "fused paged decode core: block-table gather + "
                        "GQA online-softmax attend in one pass, no "
                        "gathered KV intermediate (nn/paged_attention, "
                        "backend-auto kernel)"},
        {"name": "paged_fused_chunk_attention", "fn": fused_chunk_attn,
         "args": fused_chunk_args,
         "jit_kwargs": {"donate_argnums": (2, 3)},
         "description": "fused paged chunk core (spec-verify width "
                        "k+1): per-lane-offset queries, batched KV "
                        "scatter + fused block-table attend"},
        {"name": "prefill_flash_attention", "fn": prefill_attn,
         "args": prefill_args,
         "description": "causal prompt-phase attention array kernel"},
    ]


def tracked_program_specs(names=None):
    """Build the registry (or the named subset). Builders run lazily so
    `--programs cached_decode_attention` never constructs an engine."""
    want = set(names) if names else set(TRACKED_PROGRAMS)
    unknown = want - set(TRACKED_PROGRAMS)
    if unknown:
        raise ValueError(f"unknown tracked programs {sorted(unknown)}; "
                         f"registry has {list(TRACKED_PROGRAMS)}")
    specs = []
    if want & {"serving_decode_wave", "serving_prefill"}:
        specs += [s for s in _serving_specs() if s["name"] in want]
    if want & {"paged_decode_wave", "paged_prefill_chunk"}:
        specs += [s for s in _paged_serving_specs() if s["name"] in want]
    if want & {"paged_spec_draft_wave", "paged_spec_verify"}:
        specs += [s for s in _spec_serving_specs() if s["name"] in want]
    if "train_step" in want:
        specs.append(_train_step_spec())
    if "sharded_train_step" in want:
        specs.append(_sharded_train_step_spec())
    if "sharded_train_step_z3" in want:
        specs.append(_sharded_train_step_spec(
            zero_stage=3, name="sharded_train_step_z3"))
    if "sharded_decode_wave" in want:
        specs.append(_sharded_decode_wave_spec())
    if want & {"cached_decode_attention", "paged_decode_attention",
               "paged_fused_decode_attention",
               "paged_fused_chunk_attention", "prefill_flash_attention"}:
        specs += [s for s in _attention_specs() if s["name"] in want]
    return specs
