"""Tracked-program registry: the compiled programs the repo gates on.

Each entry is a deterministic abstract-shape lowering spec — a tiny
canonical configuration (2 layers, hidden <= 128: HLO structure, not
capacity, is what's audited, and tier-1 shares the 870s budget) of a
REAL hot path:

  * `serving_decode_wave` / `serving_prefill` — the ServingEngine's two
    programs, lowered from the engine's own raw closures (the engine
    stashes them precisely so this audit and the serving path cannot
    drift apart);
  * `train_step` — `jit.TrainStep` (forward + backward + AdamW, donated
    state) on the canonical 2-layer GPT config — the same topology
    bench.py's CPU smoke compiles, so the persistent compile cache is
    shared;
  * `cached_decode_attention` — the GQA single-token cached attention
    core from nn/transformer.py with a per-slot position VECTOR (the
    serving decode regime);
  * `prefill_flash_attention` — the causal prompt-phase attention array
    kernel the prefill paths route through.

Specs are dicts: {name, fn | jitted, args, jit_kwargs, description}.
Builders reset the global seed so repeated snapshots are
bit-deterministic; parameter VALUES never reach the lowering anyway —
only shapes/dtypes do.
"""

# serving canonical shape (mirrors tests/test_serving.py scale)
SERVING = dict(vocab=128, hidden=64, layers=2, heads=4, max_len=64,
               prefill_len=16, num_slots=4)
# train canonical shape == bench.py CPU-smoke config
TRAIN = dict(vocab=512, hidden=128, layers=2, heads=4, seq=128, batch=2)

TRACKED_PROGRAMS = ("serving_decode_wave", "serving_prefill",
                    "train_step", "cached_decode_attention",
                    "prefill_flash_attention")


def engine_program_specs(engine, prefix="serving"):
    """Audit specs for a LIVE ServingEngine's two programs, with the
    engine's actual shapes — used on the canonical engine below and by
    bench_serving.py on the engine it just measured."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    S = engine.num_slots
    key = jax.random.PRNGKey(0)
    jit_kwargs = {"donate_argnums": engine._program_donate_argnums}
    decode_args = (
        engine._params, engine._buffers, engine._caches,
        jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
        jnp.ones((S,), bool), jnp.zeros((S,), bool),
        jnp.ones((S,), jnp.float32),
        jnp.zeros((S,), bool),          # poison (chaos NaN injection)
        key)
    prefill_args = (
        engine._params, engine._buffers, engine._caches,
        jnp.asarray(np.zeros((engine.prefill_len,), np.int32)),
        jnp.int32(1), jnp.int32(0), jnp.asarray(False),
        jnp.float32(1.0), key)
    return [
        {"name": f"{prefix}_decode_wave", "fn": engine._decode_wave_fn,
         "args": decode_args, "jit_kwargs": jit_kwargs,
         "description": f"one batched decode token for every slot "
                        f"(slots={S}, max_len={engine.max_len})"},
        {"name": f"{prefix}_prefill", "fn": engine._prefill_fn,
         "args": prefill_args, "jit_kwargs": jit_kwargs,
         "description": f"one prompt bucket admission "
                        f"(prefill_len={engine.prefill_len})"},
    ]


def _serving_specs():
    import paddle_tpu as pt
    from paddle_tpu.nlp import GPTConfig, GPTForPretraining
    from paddle_tpu.serving import ServingEngine

    pt.seed(0)
    cfg = GPTConfig(vocab_size=SERVING["vocab"],
                    hidden_size=SERVING["hidden"],
                    num_layers=SERVING["layers"],
                    num_heads=SERVING["heads"],
                    max_seq_len=SERVING["max_len"],
                    dropout=0.0, attn_dropout=0.0)
    engine = ServingEngine(GPTForPretraining(cfg),
                           num_slots=SERVING["num_slots"],
                           max_len=SERVING["max_len"],
                           prefill_len=SERVING["prefill_len"])
    return engine_program_specs(engine)


def train_step_spec(step, inputs, labels):
    """Audit spec for a LIVE TrainStep: lowers the step's own compiled
    callable with its current state (injection needs a raw fn, which
    TrainStep does not expose — gate regressions via the registry's
    canonical instance instead)."""
    import jax
    import jax.numpy as jnp
    args = (step.params, step.buffers, step.opt_state, step.grad_acc,
            jax.random.PRNGKey(0), jnp.asarray(1e-4, jnp.float32),
            jnp.asarray(1, jnp.int32), tuple(inputs), tuple(labels))
    return {"name": "train_step", "jitted": step._compiled, "args": args,
            # donation metadata for the semantic audit (tools/jxaudit):
            # a prebuilt jitted carries no introspectable donate info,
            # so the spec passes the TrainStep's own declaration through
            "donate_argnums": getattr(step, "_donate_argnums", ()),
            "arg_names": ("params", "buffers", "opt_state", "acc", "key",
                          "lr", "step_i", "inputs", "labels"),
            "description": "forward+backward+optimizer, one donated "
                           "executable (canonical 2-layer GPT)"}


def _train_step_spec():
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nlp import GPTConfig, GPTForPretraining
    from paddle_tpu.nlp.gpt import gpt_pretrain_loss

    pt.seed(0)
    cfg = GPTConfig(vocab_size=TRAIN["vocab"], hidden_size=TRAIN["hidden"],
                    num_layers=TRAIN["layers"], num_heads=TRAIN["heads"],
                    max_seq_len=TRAIN["seq"], dropout=0.0,
                    attn_dropout=0.0)
    model = GPTForPretraining(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters())
    step = TrainStep(model, gpt_pretrain_loss, opt, donate=True)
    ids = np.zeros((TRAIN["batch"], TRAIN["seq"]), np.int32)
    return train_step_spec(step, (ids,), (ids,))


def _attention_specs():
    import jax.numpy as jnp
    from paddle_tpu.nn.transformer import cached_decode_attention
    from paddle_tpu.ops.pallas.flash_attention import _flash_array

    b, h, hkv, L, d = 4, 4, 2, 64, 16

    def decode_attn(q, ck, cv, pos):
        return cached_decode_attention(q, ck, cv, pos,
                                       scale=1.0 / (d ** 0.5))

    decode_args = (jnp.zeros((b, h, 1, d), jnp.float32),
                   jnp.zeros((b, hkv, L, d), jnp.float32),
                   jnp.zeros((b, hkv, L, d), jnp.float32),
                   jnp.zeros((b,), jnp.int32))

    def prefill_attn(q, k, v):
        return _flash_array(q, k, v, causal=True)

    prefill_args = (jnp.zeros((2, h, L, d), jnp.float32),
                    jnp.zeros((2, h, L, d), jnp.float32),
                    jnp.zeros((2, h, L, d), jnp.float32))
    return [
        {"name": "cached_decode_attention", "fn": decode_attn,
         "args": decode_args,
         "description": "GQA cached decode attention core, per-slot "
                        "position vector"},
        {"name": "prefill_flash_attention", "fn": prefill_attn,
         "args": prefill_args,
         "description": "causal prompt-phase attention array kernel"},
    ]


def tracked_program_specs(names=None):
    """Build the registry (or the named subset). Builders run lazily so
    `--programs cached_decode_attention` never constructs an engine."""
    want = set(names) if names else set(TRACKED_PROGRAMS)
    unknown = want - set(TRACKED_PROGRAMS)
    if unknown:
        raise ValueError(f"unknown tracked programs {sorted(unknown)}; "
                         f"registry has {list(TRACKED_PROGRAMS)}")
    specs = []
    if want & {"serving_decode_wave", "serving_prefill"}:
        specs += [s for s in _serving_specs() if s["name"] in want]
    if "train_step" in want:
        specs.append(_train_step_spec())
    if want & {"cached_decode_attention", "prefill_flash_attention"}:
        specs += [s for s in _attention_specs() if s["name"] in want]
    return specs
