"""Opcode histogram over optimized HLO text.

The compiled executable's `as_text()` is post-optimization HLO — the
program XLA actually runs, after fusion, layout assignment and
scheduling. This module reduces that text to the aggregate numbers the
audit baselines: how many instructions survived, how much of the
program lives inside fusions (and of which kind), and how many
communication ops the partitioner emitted. Those are exactly the
quantities the operator-fusion literature (PAPERS.md: "Operator Fusion
in XLA", "FusionStitching") identifies as the compile-level fingerprint
of a memory-bound program — a PR that breaks fusion on the decode hot
path moves `fusion_count`/`bytes_accessed` long before a wall-clock
bench can see it.

Text parsing (vs walking the jaxpr, graph_census.py's technique) is
deliberate: fusion decisions only exist AFTER the backend pipeline, and
the stable public surface for the optimized program in jax 0.4.37 is
the HLO text dump.
"""
import re

# one HLO instruction per line:  [ROOT] %name = type[shape]{layout} opcode(
# the type may be a TUPLE `(f32[..]{..}, s32[..]{..})` — multi-output
# fusions and tuple roots — whose spaces a bare \S+ cannot span
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(?:\([^)]*\)|\S+)\s+"
    r"([a-z][a-z0-9\-]*)")
_FUSION_KIND_RE = re.compile(r"\bkind=k(\w+)")
_CUSTOM_CALL_TARGET_RE = re.compile(r'custom_call_target="([^"]*)"')

# computation-opening lines (`%fused_computation ... {`, `ENTRY %main`)
# also contain " = " never — they match nothing; parameter declarations
# inside computations DO parse as `parameter` instructions, matching
# XLA's own instruction-count accounting.

COLLECTIVE_OPCODES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
)

# HLO type-string element sizes for operand-byte accounting; a dtype
# outside this table makes the bytes for that op None (count still
# recorded) rather than silently wrong
_HLO_TYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_OPERAND_TYPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([\d,]*)\]")


def _operand_bytes(line, op):
    """Total operand bytes of one collective instruction line, parsed
    from the typed operand list (`all-gather(f32[32,128]{1,0} %x, ...)`
    — optimized HLO text spells every operand with its type), or None
    when a type doesn't parse. Communication volume is what the operands
    carry INTO the op: for all-gather the result is dp x bigger and for
    reduce-scatter dp x smaller, so result bytes would mis-rank exactly
    the ops the budget exists to compare."""
    start = line.find(op + "(")
    if start < 0:
        return None
    i = start + len(op) + 1
    depth, buf = 1, []
    while i < len(line) and depth:
        c = line[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if not depth:
                break
        buf.append(c)
        i += 1
    total = 0
    matched = False
    for m in _OPERAND_TYPE_RE.finditer("".join(buf)):
        size = _HLO_TYPE_BYTES.get(m.group(1))
        if size is None:
            return None
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n * size
        matched = True
    return total if matched else None


def op_histogram(hlo_text):
    """Reduce optimized HLO text to the audit's aggregate counts.

    Returns a plain JSON-able dict:
      instruction_count  — instructions across every computation
      fusion_count       — `fusion(...)` instructions
      fusion_kinds       — {"Loop": n, "Output": n, ...} per kind=kXxx
      collective_count   — communication ops (incl. -start variants)
      collectives        — per-opcode counts for the comm ops present
      collective_bytes   — per-opcode total OPERAND bytes for those ops
                           (None for an opcode whose operand types did
                           not parse); communication volume, the number
                           EQuARX-style collective work is gated on
      collective_bytes_total — sum of the parseable per-op bytes
      custom_call_count  — custom-call instructions (host callbacks,
                           library kernels — the un-fusable opaque ops)
      custom_calls       — {target: count} per custom_call_target — a
                           Pallas kernel shows up here by name (e.g.
                           "tpu_custom_call"), which keeps the
                           fusion-count gate meaningful: work moving
                           from XLA fusions INTO an opaque kernel is
                           visible as a named count, not a silent
                           fusion_count drop
      ops                — full opcode -> count histogram
    Deterministic for a given program + backend: names/ids are ignored,
    only opcodes, fusion kinds and custom-call targets are counted.
    """
    ops = {}
    fusion_kinds = {}
    custom_calls = {}
    coll_bytes = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        op = m.group(1)
        ops[op] = ops.get(op, 0) + 1
        if op == "fusion":
            k = _FUSION_KIND_RE.search(line)
            kind = k.group(1) if k else "Unknown"
            fusion_kinds[kind] = fusion_kinds.get(kind, 0) + 1
        elif op == "custom-call":
            t = _CUSTOM_CALL_TARGET_RE.search(line)
            target = t.group(1) if t else "unknown"
            custom_calls[target] = custom_calls.get(target, 0) + 1
        else:
            base = op[:-6] if op.endswith("-start") else op
            if base in COLLECTIVE_OPCODES:
                nbytes = _operand_bytes(line, op)
                if op in coll_bytes and (nbytes is None
                                         or coll_bytes[op] is None):
                    coll_bytes[op] = None
                else:
                    coll_bytes[op] = coll_bytes.get(op, 0) + nbytes \
                        if nbytes is not None else None
    collectives = {}
    for op, n in ops.items():
        base = op[:-6] if op.endswith("-start") else op
        if base in COLLECTIVE_OPCODES:
            collectives[op] = n
    return {
        "instruction_count": sum(ops.values()),
        "fusion_count": ops.get("fusion", 0),
        "fusion_kinds": dict(sorted(fusion_kinds.items())),
        "collective_count": sum(collectives.values()),
        "collectives": dict(sorted(collectives.items())),
        "collective_bytes": dict(sorted(coll_bytes.items())),
        "collective_bytes_total": sum(
            v for v in coll_bytes.values() if v is not None),
        "custom_call_count": ops.get("custom-call", 0),
        "custom_calls": dict(sorted(custom_calls.items())),
        "ops": dict(sorted(ops.items())),
    }
