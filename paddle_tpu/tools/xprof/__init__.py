"""xprof: the XLA program observatory.

Compile-level observability for the repo's tracked hot paths — HLO
cost analysis (flops / bytes-accessed), compiled memory analysis
(argument / output / temp / peak bytes) and an optimized-HLO opcode
histogram (fusions, collectives, instruction count) per program, with
a committed baseline (`scripts/hlo_baseline.json`) and a regression
gate (`scripts/hlo_audit.py --diff`, tier-1 via
tests/test_hlo_audit.py). See docs/observability.md ("XLA program
observatory").
"""
from . import audit, hlo, registry                        # noqa: F401
from .audit import (audit_jitted, diff, publish, rollup,   # noqa: F401
                    snapshot_programs)
from .registry import (engine_program_specs,               # noqa: F401
                       tracked_program_specs, train_step_spec)
