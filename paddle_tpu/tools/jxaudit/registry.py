"""jxaudit program registry: xprof's tracked programs + extras.

The auditable surface is:

  * every program the xprof observatory tracks (serving decode wave +
    prefill lowered from the engine's own stashed closures, the
    compiled train step, the attention cores) — one registry of record,
    so the semantic audit and the cost audit can never diverge on WHAT
    they audit;
  * ``optimizer_update`` — the eager per-parameter optimizer executable
    (`optimizer._jitted_update`), which the train-step program does NOT
    cover (TrainStep folds the update into its own donated program;
    eager `Model.fit` / `opt.step()` training runs this one);
  * anything registered through the :func:`audited` decorator — the
    hook for new subsystems to opt their hot programs into the audit
    without touching this module.
"""
AUDITED = {}


def audited(name=None, *, args=None, jit_kwargs=None, donate_argnums=None,
            arg_names=None, description=None):
    """Decorator: register a function as a jxaudit-tracked program.

        @jxaudit.audited("paged_attention",
                         args=lambda: (q, kv, tables),
                         jit_kwargs={"donate_argnums": (1,)})
        def paged_attention(q, kv, tables): ...

    ``args`` is the example-argument tuple or a zero-arg callable
    building one lazily (evaluated only when the audit runs — never at
    import). The decorated function is returned unchanged."""
    def deco(fn):
        prog = name or fn.__name__
        if prog in AUDITED or prog in _builtin_names():
            raise ValueError(f"jxaudit program {prog!r} already "
                             "registered")
        AUDITED[prog] = {
            "fn": fn, "args": args, "jit_kwargs": dict(jit_kwargs or {}),
            "donate_argnums": donate_argnums, "arg_names": arg_names,
            "description": description,
        }
        return fn
    return deco


def _builtin_names():
    from ..xprof import registry as xprof_registry
    return xprof_registry.TRACKED_PROGRAMS + ("optimizer_update",)


def audited_program_specs(names=None):
    """Build specs for decorator-registered programs (lazy args)."""
    specs = []
    for prog, row in sorted(AUDITED.items()):
        if names is not None and prog not in names:
            continue
        args = row["args"]
        if callable(args):
            args = args()
        spec = {"name": prog, "fn": row["fn"], "args": tuple(args or ()),
                "jit_kwargs": row["jit_kwargs"]}
        if row["donate_argnums"] is not None:
            spec["donate_argnums"] = tuple(row["donate_argnums"])
        if row["arg_names"]:
            spec["arg_names"] = tuple(row["arg_names"])
        if row["description"]:
            spec["description"] = row["description"]
        specs.append(spec)
    return specs


# canonical shape for the eager optimizer update: one mid-sized layer's
# weight matrix (1 MiB param, 2 MiB Adam state) — structure is what the
# rules inspect, capacity is irrelevant
OPT_UPDATE_SHAPE = (512, 512)


def _optimizer_update_spec():
    import jax.numpy as jnp
    from ...optimizer import optimizer as opt_mod

    p = jnp.zeros(OPT_UPDATE_SHAPE, jnp.float32)
    g = jnp.ones(OPT_UPDATE_SHAPE, jnp.float32)
    state = (jnp.zeros_like(p), jnp.zeros_like(p))   # AdamW (m, v)
    hyper = (0.9, 0.999, 1e-8, 0.01)
    args = (p, g, jnp.asarray(1e-3, jnp.float32), hyper, state,
            jnp.asarray(1, jnp.int32))
    return {
        "name": "optimizer_update",
        "fn": opt_mod.AdamW._update,
        "args": args,
        # the wrapper optimizer.step() actually calls, with ITS donation
        # declaration — read from the one constant _jitted_update uses,
        # so this spec cannot drift from the eager training path
        "jitted": opt_mod._jitted_update(opt_mod.AdamW),
        "donate_argnums": opt_mod.UPDATE_DONATE_ARGNUMS,
        "arg_names": ("p", "g", "lr", "hyper", "state", "step"),
        "description": "eager per-parameter AdamW update (the "
                       "opt.step() executable, one (512,512) leaf)",
    }


def tracked_specs(names=None):
    """All audited program specs (or the named subset): the xprof
    registry's five, ``optimizer_update``, and decorator registrations.
    Builders run lazily — auditing one attention core never constructs
    an engine."""
    from ..xprof import registry as xprof_registry

    # the decorator refuses collisions with built-in names, so `known`
    # is duplicate-free by construction
    known = _builtin_names() + tuple(sorted(AUDITED))
    want = list(names) if names else list(known)
    unknown = set(want) - set(known)
    if unknown:
        raise ValueError(f"unknown audited programs {sorted(unknown)}; "
                         f"registry has {list(known)}")
    specs = []
    xprof_names = [n for n in want if n in xprof_registry.TRACKED_PROGRAMS]
    if xprof_names:
        specs += xprof_registry.tracked_program_specs(xprof_names)
    if "optimizer_update" in want:
        specs.append(_optimizer_update_spec())
    specs += audited_program_specs([n for n in want if n in AUDITED])
    order = {n: i for i, n in enumerate(want)}
    specs.sort(key=lambda s: order.get(s["name"], len(order)))
    return specs


def tracked_program_names():
    """Current full program-name tuple (decorators may add to it)."""
    return _builtin_names() + tuple(sorted(AUDITED))


# the pjit-over-a-mesh subset the mesh-aware rule family (shaudit)
# audits: programs whose specs carry a "sharding" declaration
MESH_PROGRAMS = ("sharded_train_step", "sharded_train_step_z3",
                 "sharded_decode_wave")


def mesh_specs(names=None):
    """Specs for the sharded tracked programs (or the named subset) —
    the shaudit CLI's default audit surface."""
    want = list(names) if names else list(MESH_PROGRAMS)
    unknown = set(want) - set(MESH_PROGRAMS)
    if unknown:
        raise ValueError(f"unknown mesh programs {sorted(unknown)}; "
                         f"registry has {list(MESH_PROGRAMS)}")
    return tracked_specs(want)
