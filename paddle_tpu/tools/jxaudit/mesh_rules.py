"""jxaudit mesh-aware rules: sharding & collective semantics of pjit
programs (the `shaudit` CLI's rule family).

These rules audit the registry's SHARDED tracked programs — pjit'd
modules compiled over an explicit device mesh — by comparing three
layers that are supposed to agree but are maintained by different
hands:

  1. what the code DECLARED — the live PartitionSpec trees and
     constraint sites threaded through ``spec["sharding"]`` (e.g.
     ``ShardedTrainStep.audit_sharding_decl``, so declarations cannot
     drift from the jit call they describe);
  2. what XLA COMMITTED — the ``sharding={...}`` annotations on the
     optimized module's entry parameters
     (``core.parse_entry_param_shardings``) and the collective
     instructions the partitioner emitted (``xprof.hlo.op_histogram``);
  3. what was BANKED — the per-opcode collective {count, bytes} rows in
     scripts/hlo_baseline.json.

Rules live in their OWN registry (``MESH_RULES``) so the jxaudit and
shaudit CLIs stay disjoint rule sets over one driver
(``core.audit_programs(..., rules=MESH_RULES)``); every rule degrades
to null+reason exactly like the built-ins — a single-device build, a
module whose text carries no annotations, or a failed ``lower()`` must
never misattribute.

The spec's ``sharding`` dict:

  mesh_axes             {axis_name: size} of the declared mesh
  in_specs              {argnum: PartitionSpec | pytree of specs} — a
                        bare spec is a PREFIX (covers every leaf of
                        that arg), mirroring jit's in_shardings
  constraint_specs      [repr(PartitionSpec), ...] with_sharding_
                        constraint sites the traced program must carry
  expected_collectives  collective opcodes reshard-in-body must NOT
                        flag (declared, justified data movement — e.g.
                        flash-attention halo exchanges)
  collective_baseline   attached by the CLI from hlo_baseline.json:
                        {"collectives": {op: {count, bytes}},
                         "tolerances": {...}}
"""
from . import core as _core
from .core import Rule, iter_eqns, leaf_nbytes, aval_type_str
from .rules import (DONATABLE_STATE_MIN_BYTES, STATE_ARG_NAMES,
                    DonationDropped)

MESH_RULES = {}

# implicit-reshard collective opcodes: all-to-all IS the partitioner's
# spelling of a layout transpose (sharded axis moves), and a
# collective-permute outside the declared expected set means data is
# being rotated between devices no constraint asked for. all-reduce /
# all-gather / reduce-scatter are NOT here — they are how legitimate
# sharded math (grad sync, gather-on-use) is spelled, and their counts
# are gated exactly by collective-budget instead.
RESHARD_OPCODES = ("all-to-all", "collective-permute")


def register_mesh(cls):
    """Class decorator: instantiate into the MESH registry, refusing
    any id collision with the built-in jxaudit rules — the three CLIs'
    --list-rules are documented (and tested) disjoint."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in MESH_RULES or inst.id in _core.RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    MESH_RULES[inst.id] = inst
    return cls


# ---------------------------------------------------------------------------
# declaration plumbing
# ---------------------------------------------------------------------------

def _spec_axes(spec):
    """Flat mesh-axis names a PartitionSpec actually uses (entries can
    be None, a name, or a tuple of names)."""
    axes = []
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.extend(entry)
        else:
            axes.append(entry)
    return axes


def _is_replicated(committed):
    """True when a committed HLO sharding string means fully
    replicated. Exact-match on the canonical spelling — `{devices=...
    last_tile_dim_replicate}` is PARTIAL replication and must not
    match."""
    return committed.replace(" ", "") == "{replicated}"


def leaf_rows(ctx):
    """Flatten the declared per-arg specs against the actual args ->
    ([(flat_leaf_index, argnum, label, leaf, spec_or_None), ...], None)
    or (None, reason). Labels are ``argname + keypath`` (stable across
    runs — dict flattening is key-sorted). A bare PartitionSpec
    declaration is a prefix covering every leaf of its arg."""
    import jax
    from jax.sharding import PartitionSpec
    meta = ctx.spec.get("sharding") or {}
    in_specs = meta.get("in_specs") or {}
    names = ctx.arg_names
    rows, flat = [], 0
    for argnum, arg in enumerate(ctx.args):
        paths = jax.tree_util.tree_flatten_with_path(arg)[0]
        decl = in_specs.get(argnum)
        if isinstance(decl, PartitionSpec):
            specs = [decl] * len(paths)
        elif decl is not None:
            specs = jax.tree_util.tree_leaves(
                decl, is_leaf=lambda x: isinstance(x, PartitionSpec))
            if len(specs) != len(paths):
                return None, (
                    f"declared in_specs for arg #{argnum} flatten to "
                    f"{len(specs)} spec leaves but the arg has "
                    f"{len(paths)} — the declaration drifted from the "
                    "argument structure")
        else:
            specs = [None] * len(paths)
        base = (names[argnum] if names and argnum < len(names)
                else f"#{argnum}")
        for i, (path, leaf) in enumerate(paths):
            rows.append((flat + i, argnum,
                         base + jax.tree_util.keystr(path), leaf,
                         specs[i]))
        flat += len(paths)
    return rows, None


def _committed_views(ctx, rule):
    """(entry_param_shardings, leaf_param_map) or (None, None) after
    degrading `rule` with the blocking reason."""
    ann = ctx.entry_param_shardings
    if ann is None:
        ctx.degrade(rule.id, "entry sharding annotations unavailable: "
                    + ctx.unavailable.get("entry_param_shardings", "?"))
        return None, None
    mapping = ctx.leaf_param_map
    if mapping is None:
        ctx.degrade(rule.id, "cannot map arg leaves onto compiled "
                    "entry parameters: "
                    + ctx.unavailable.get("leaf_param_map", "?"))
        return None, None
    return ann, mapping


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

@register_mesh
class ShardingDropped(Rule):
    id = "sharding-dropped"
    severity = "error"
    rationale = ("A declared in-sharding XLA silently committed as "
                 "fully replicated (or a with_sharding_constraint site "
                 "a refactor traced away) undoes the memory/compute "
                 "partitioning the code asked for — the program still "
                 "runs, just dp-times bigger, and only a compile-level "
                 "diff can see it.")

    def check(self, ctx):
        meta = ctx.spec.get("sharding")
        if not meta:
            ctx.degrade(self.id, "spec carries no declared sharding "
                        "metadata (not a mesh program?)")
            return
        rows, reason = leaf_rows(ctx)
        if rows is None:
            ctx.degrade(self.id, reason)
            return
        declared = [r for r in rows
                    if r[4] is not None and _spec_axes(r[4])]
        if declared:
            ann, mapping = _committed_views(ctx, self)
            if ann is None:
                return
            for flat, argnum, label, leaf, spec in declared:
                pi = mapping.get(flat)
                if pi is None:
                    continue    # pruned arg: nothing was committed
                committed = ann.get(pi)
                if committed is None:
                    ctx.degrade(self.id, f"entry parameter {pi} "
                                f"({label}) carries no sharding "
                                "annotation")
                    continue
                if _is_replicated(committed):
                    yield ctx.finding(
                        self.id,
                        f"declared sharding {spec} for {label} was "
                        "dropped — XLA committed this entry parameter "
                        "fully replicated",
                        severity=self.severity,
                        details={"leaf": label, "declared": repr(spec),
                                 "committed": committed,
                                 "entry_param": pi})
        wanted = list(meta.get("constraint_specs") or ())
        if not wanted:
            return
        cj = ctx.closed_jaxpr
        if cj is None:
            ctx.degrade(self.id, "jaxpr unavailable: "
                        + ctx.unavailable.get("jaxpr", "?"))
            return
        present = set()
        for eqn in iter_eqns(cj.jaxpr):
            if getattr(eqn.primitive, "name",
                       str(eqn.primitive)) == "sharding_constraint":
                sh = eqn.params.get("sharding")
                present.add(repr(getattr(sh, "spec", sh)))
        for want in wanted:
            if want not in present:
                yield ctx.finding(
                    self.id,
                    f"declared with_sharding_constraint {want} has no "
                    "site left in the traced program — the constraint "
                    "was dropped",
                    severity=self.severity,
                    details={"declared": want,
                             "present": sorted(present)})


@register_mesh
class AccidentalReplication(Rule):
    id = "accidental-replication"
    severity = "error"
    rationale = ("A large state tensor (optimizer moments, KV cache) "
                 "compiled fully replicated along a >1-size mesh axis "
                 "pays (devices-1)x its bytes in HBM for nothing — the "
                 "silent memory tax arXiv:2004.13336 measures; ZeRO "
                 "exists precisely to shard these.")

    def check(self, ctx):
        meta = ctx.spec.get("sharding")
        if not meta:
            ctx.degrade(self.id, "spec carries no declared sharding "
                        "metadata (not a mesh program?)")
            return
        axes = {a: int(s) for a, s in (meta.get("mesh_axes") or
                                       {}).items() if int(s) > 1}
        if not axes:
            return      # 1-device mesh: replication is free
        names = ctx.arg_names
        if names is None:
            ctx.degrade(self.id, "positional arg names unavailable "
                        "(prebuilt jitted spec without arg_names)")
            return
        rows, reason = leaf_rows(ctx)
        if rows is None:
            ctx.degrade(self.id, reason)
            return
        ann, mapping = _committed_views(ctx, self)
        if ann is None:
            return
        ndev = 1
        for s in (meta.get("mesh_axes") or {}).values():
            ndev *= int(s)
        for flat, argnum, label, leaf, spec in rows:
            if argnum >= len(names) \
                    or names[argnum] not in STATE_ARG_NAMES:
                continue
            nbytes = leaf_nbytes(leaf)
            if nbytes < DONATABLE_STATE_MIN_BYTES:
                continue
            pi = mapping.get(flat)
            if pi is None:
                continue
            committed = ann.get(pi)
            if committed is None or not _is_replicated(committed):
                continue
            shape = getattr(leaf, "shape", ())
            if not any(d and d % size == 0
                       for d in shape for size in axes.values()):
                continue    # no mesh axis divides any dim: unshardable
            yield ctx.finding(
                self.id,
                f"state leaf {label} ({aval_type_str(leaf)}) is "
                f"compiled fully replicated across the {ndev}-device "
                "mesh despite a shardable dim — every device holds a "
                "full copy",
                severity=self.severity,
                details={"leaf": label, "bytes": nbytes,
                         "wasted_bytes": nbytes * (ndev - 1),
                         "mesh_axes": dict(meta.get("mesh_axes") or {}),
                         "entry_param": pi})


@register_mesh
class DonationThroughPjit(DonationDropped):
    # DonationDropped's check already works at per-shard shapes — the
    # leaf/param alignment types each concrete leaf by its
    # sharding.shard_shape (core.leaf_shard_shape), which is how a
    # partitioned module's entry parameters are spelled. Re-registered
    # under its own id so the MESH registry gates it on the sharded
    # programs (and the built-in registry's findings stay attributed to
    # 'donation-dropped' for the single-device ones).
    id = "donation-through-pjit"
    severity = "error"
    rationale = ("Donation is declared per logical arg but committed "
                 "per SHARD: an output whose dtype/per-shard shape no "
                 "longer matches the donated input drops the alias on "
                 "every device at once — dp copies of the double-"
                 "buffering HBM cost donation-dropped flags on one.")


@register_mesh
class CollectiveBudget(Rule):
    id = "collective-budget"
    severity = "error"
    rationale = ("Collectives are the scaling-cost primitives (EQuARX: "
                 "count AND operand bytes are the gate metric); an "
                 "accidental all-gather on a hot path is invisible to "
                 "unit tests and shows up in benches as an unexplained "
                 "regression — gate the per-opcode histogram against "
                 "the banked budget instead.")

    def check(self, ctx):
        text = ctx.hlo_text
        if text is None:
            ctx.degrade(self.id, "compiled HLO unavailable: "
                        + ctx.unavailable.get("hlo_text", "?"))
            return
        meta = ctx.spec.get("sharding") or {}
        base = meta.get("collective_baseline")
        if base is None:
            ctx.degrade(self.id, meta.get(
                "collective_baseline_reason",
                "no banked collective rows for this program — bank "
                "them via scripts/hlo_audit.py --update-baseline"))
            return
        from ..xprof import hlo as hlo_mod
        hist = hlo_mod.op_histogram(text)
        rows = base.get("collectives") or {}
        tols = base.get("tolerances") or {}
        count_tol = tols.get("collective_count") or {}
        bytes_tol = tols.get("collective_bytes") or {}
        cur_counts = hist.get("collectives") or {}
        cur_bytes = hist.get("collective_bytes") or {}
        for op in sorted(cur_counts):
            row = rows.get(op)
            if row is None:
                yield ctx.finding(
                    self.id,
                    f"unbudgeted collective '{op}' appeared in this "
                    "program (zero banked budget) — an accidental "
                    "communication op on the hot path",
                    severity=self.severity,
                    details={"op": op, "count": cur_counts[op],
                             "bytes": cur_bytes.get(op)})
                continue
            b = row.get("count")
            if b is not None and cur_counts[op] > _limit(b, count_tol):
                yield ctx.finding(
                    self.id,
                    f"collective '{op}' count exceeded its banked "
                    "budget",
                    severity=self.severity,
                    details={"op": op, "base": b,
                             "current": cur_counts[op],
                             "limit": _limit(b, count_tol)})
            bb, cb = row.get("bytes"), cur_bytes.get(op)
            if bb is not None and cb is not None \
                    and cb > _limit(bb, bytes_tol):
                yield ctx.finding(
                    self.id,
                    f"collective '{op}' operand bytes exceeded the "
                    "banked budget",
                    severity=self.severity,
                    details={"op": op, "base_bytes": bb,
                             "current_bytes": cb,
                             "limit": _limit(bb, bytes_tol)})


def _limit(base, tol):
    return base * (1.0 + tol.get("rtol", 0.0)) + tol.get("atol", 0.0)


@register_mesh
class ReshardInBody(Rule):
    id = "reshard-in-body"
    severity = "error"
    rationale = ("A producer/consumer sharding mismatch inside the "
                 "module makes the partitioner insert an implicit "
                 "reshard collective (all-to-all / collective-permute) "
                 "no declared constraint asked for — per-step data "
                 "motion the source never spelled, usually a "
                 "PartitionSpec typo or a propagation surprise.")

    def check(self, ctx):
        meta = ctx.spec.get("sharding")
        if not meta:
            ctx.degrade(self.id, "spec carries no declared sharding "
                        "metadata (not a mesh program?)")
            return
        text = ctx.hlo_text
        if text is None:
            ctx.degrade(self.id, "compiled HLO unavailable: "
                        + ctx.unavailable.get("hlo_text", "?"))
            return
        from ..xprof import hlo as hlo_mod
        hist = hlo_mod.op_histogram(text)
        expected = set(meta.get("expected_collectives") or ())
        cur_bytes = hist.get("collective_bytes") or {}
        for op, n in sorted((hist.get("collectives") or {}).items()):
            base = op[:-6] if op.endswith("-start") else op
            if base not in RESHARD_OPCODES or base in expected \
                    or op in expected:
                continue
            yield ctx.finding(
                self.id,
                f"implicit reshard: collective '{base}' in the "
                "compiled body with no declared constraint or "
                "expected-collective asking for it",
                severity=self.severity,
                details={"op": op, "count": n,
                         "bytes": cur_bytes.get(op),
                         "expected_collectives": sorted(expected)})


# ---------------------------------------------------------------------------
# summary / journal
# ---------------------------------------------------------------------------

def summarize_mesh(findings, report):
    """core.summarize + the mesh-specific aggregates the journal and
    runlog summary render: total wasted replicated HBM and the number
    of collective-budget breaches."""
    s = _core.summarize(findings, report)
    s["wasted_replicated_bytes"] = int(sum(
        f.details.get("wasted_bytes") or 0 for f in findings
        if f.rule == "accidental-replication"))
    s["collective_breaches"] = sum(
        1 for f in findings if f.rule == "collective-budget")
    return s


def publish_mesh_summary(findings, report, recorder=None, **extra):
    """Journal a ``shaudit`` summary event through ``recorder`` or the
    current flight recorder — same contract as core.publish_summary
    (pass POST-baseline findings so the journaled verdict matches the
    exit code). No-op without a recorder."""
    from ...utils import flight_recorder as fr
    rec = recorder if recorder is not None else fr.get_recorder()
    if rec is None:
        return None
    s = summarize_mesh(findings, report)
    return rec.shaudit(
        findings=s["findings"], by_rule=s["by_rule"],
        programs=s["programs"], degraded=s["degraded"],
        wasted_replicated_bytes=s["wasted_replicated_bytes"],
        collective_breaches=s["collective_breaches"], **extra)
