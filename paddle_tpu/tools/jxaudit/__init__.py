"""jxaudit: program-level (jaxpr / compiled-HLO) semantic auditor.

ptlint (tools/lint) polices the *source text* and the xprof observatory
(tools/xprof) measures the *cost* of compiled programs; this package
checks the *semantics* of what actually got traced and compiled — the
defect classes that pass both neighbours today and only ever surface as
a bench regression:

  * a buffer declared in ``donate_argnums`` that XLA silently did not
    alias (``donation-dropped``), or donatable state that is never
    donated at all (``donation-missing``) — the dominant
    silent-memory-waste class per "Automatic Cross-Replica Sharding of
    Weight Update in Data-Parallel Training" (PAPERS.md);
  * a large low-precision tensor upcast to f32/f64 on the device path
    (``dtype-leak``) — dtype-conversion ops are what breaks
    producer-consumer fusion ("Operator Fusion in XLA", PAPERS.md);
  * a weight-sized array baked into the program as a closure constant
    (``baked-constant``) — recompile-per-weight-set plus duplicated HBM;
  * a host callback reachable from a hot program (``host-callback``).

Audited programs are the xprof registry's tracked programs (the serving
decode wave/prefill lowered from the engine's own closures, the
compiled train step, the attention cores) plus the eager optimizer
update and anything registered through the :func:`audited` decorator.
Analyses degrade to null + reason on jax builds that can't answer,
mirroring xprof. CLI: ``scripts/jxaudit.py`` (exit 0 clean / 1 findings
/ 2 internal error) against the justified baseline
``scripts/jxaudit_baseline.json``. Rule catalog:
docs/static_analysis.md ("Program-level rules").

The MESH-AWARE rule family (mesh_rules.py: sharding-dropped,
accidental-replication, donation-through-pjit, collective-budget,
reshard-in-body) audits the pjit'd sharded programs over their declared
PartitionSpecs, the compiled module's committed ``sharding=``
annotations, and the banked per-opcode collective budgets. It lives in
its own registry (``MESH_RULES``) behind its own CLI
(``scripts/shaudit.py``, baseline ``scripts/shaudit_baseline.json``) —
disjoint rule ids, one shared driver. Catalog: docs/static_analysis.md
("Mesh-aware rules").
"""
from .core import (Finding, ProgramContext, RULES, register,
                   audit_programs, summarize, publish_summary)
from .registry import (audited, audited_program_specs, tracked_specs,
                       tracked_program_names, mesh_specs, MESH_PROGRAMS)
from .inject import INJECTIONS, inject_spec
from . import rules  # noqa: F401  (registers the built-in rules)
from .mesh_rules import (MESH_RULES, summarize_mesh,
                         publish_mesh_summary)
from .mesh_inject import MESH_INJECTIONS, build_injected_spec

__all__ = [
    "Finding", "ProgramContext", "RULES", "register", "audit_programs",
    "summarize", "publish_summary", "audited", "audited_program_specs",
    "tracked_specs", "tracked_program_names", "INJECTIONS",
    "inject_spec", "mesh_specs", "MESH_PROGRAMS", "MESH_RULES",
    "summarize_mesh", "publish_mesh_summary", "MESH_INJECTIONS",
    "build_injected_spec",
]
