"""shaudit positive controls: one deliberately mis-sharded probe
program per mesh rule class.

The sharded tracked programs carry prebuilt pjit callables (no raw fn
to wrap, and a ShardedTrainStep compile is seconds even warm), so —
unlike jxaudit's inject.py, which wraps the decode wave — each control
here BUILDS a tiny self-contained pjit program over the tier-1
8-device dp mesh carrying exactly one defect:

  sharding-dropped       the declaration says params are dp-sharded,
                         the live jit call compiles them replicated —
                         declaration drift, the rule's reason to exist
  accidental-replication a 512 KiB ZeRO-style optimizer accumulator
                         deliberately placed (and declared) fully
                         replicated along dp=8
  collective-budget      a correctly sharded program shipped with an
                         EMPTY banked budget, so its inherent
                         all-gather reads as unbudgeted
  donation-through-pjit  a donated dp-sharded accumulator whose
                         updated value is returned as bf16 — the alias
                         drops at per-shard shapes
  reshard-in-body        a forced with_sharding_constraint flips the
                         accumulator from P('dp', None) to
                         P(None, 'dp') mid-body: the partitioner must
                         emit all-to-all resharding collectives

``build_injected_spec(defect)`` returns the full program spec
(``injected`` set), audit-ready; the probes compile in ~1-2 s each on
the CPU mesh. On a 1-device build the probes still build (dp=1) but
the defects cannot manifest — tier-1 runs under the 8-device
XLA_FLAGS env, where each control must exit 1.
"""

PROBE_NAME = "sharded_probe"

_W, _K = 512, 256      # the m accumulator: 512*256*4 = 512 KiB f32


def _mesh():
    import numpy as np
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    dp = min(8, len(devs))
    return Mesh(np.asarray(devs[:dp]).reshape(dp), ("dp",))


def _base_fn():
    """A minimal train-ish step: matmul forward, gradient-shaped
    reduction, EMA accumulator update — enough structure for sharding
    propagation and donation to behave like the real step."""
    import jax.numpy as jnp

    def probe(params, opt_state, x):
        w = params["w"]
        g = x.T @ jnp.tanh(x @ w)
        m = opt_state["m"] * 0.9 + g * 0.1
        return {"w": w - 0.01 * m}, {"m": m}

    return probe


def _assemble(mesh, fn, param_spec, opt_spec, out_param_spec=None,
              out_opt_spec=None, meta_in_specs=None, meta_extra=None,
              description=""):
    """Shared probe-spec assembly: device_put the example args onto
    their LIVE placements, build matching in/out_shardings (opt_state
    donated), and attach the declared-sharding metadata."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    ns = lambda spec: NamedSharding(mesh, spec)
    params = {"w": jnp.ones((_W, _K), jnp.float32)}
    opt = {"m": jnp.zeros((_W, _K), jnp.float32)}
    x = jnp.ones((8, _W), jnp.float32)
    in_sh = (ns(param_spec), ns(opt_spec), ns(P()))
    args = tuple(jax.device_put(a, sh)
                 for a, sh in zip((params, opt, x), in_sh))
    out_sh = (ns(out_param_spec if out_param_spec is not None
                 else param_spec),
              ns(out_opt_spec if out_opt_spec is not None
                 else opt_spec))
    meta = {
        "mesh_axes": {a: int(mesh.shape[a]) for a in mesh.axis_names},
        "in_specs": dict(meta_in_specs if meta_in_specs is not None
                         else {0: param_spec, 1: opt_spec}),
        "constraint_specs": [],
        "expected_collectives": (),
    }
    meta.update(meta_extra or {})
    return {
        "name": PROBE_NAME, "fn": fn, "args": args,
        "jit_kwargs": {"in_shardings": in_sh, "out_shardings": out_sh,
                       "donate_argnums": (1,)},
        "donate_argnums": (1,),
        "arg_names": ("params", "opt_state", "x"),
        "sharding": meta,
        "description": description,
    }


def _inject_sharding_dropped():
    from jax.sharding import PartitionSpec as P
    mesh = _mesh()
    dp = P("dp", None) if mesh.shape["dp"] > 1 else P()
    # declaration drift: metadata claims params are dp-sharded, the
    # live in_shardings compile them replicated. opt stays honestly
    # sharded so the other rules see nothing.
    return _assemble(
        mesh, _base_fn(), param_spec=P(), opt_spec=dp,
        meta_in_specs={0: {"w": P("dp", None)}, 1: {"m": dp}},
        description="declared dp-sharded params compiled replicated "
                    "(declaration drift)")


def _inject_accidental_replication():
    from jax.sharding import PartitionSpec as P
    mesh = _mesh()
    # the deliberately replicated ZeRO accumulator: m is 512 KiB of
    # per-device state with a dp-divisible dim, placed (and declared)
    # fully replicated — every device holds all of it
    return _assemble(
        mesh, _base_fn(), param_spec=P(), opt_spec=P(),
        description="512 KiB optimizer accumulator deliberately "
                    "replicated along dp")


def _inject_collective_budget():
    from jax.sharding import PartitionSpec as P
    mesh = _mesh()
    dp = P("dp", None) if mesh.shape["dp"] > 1 else P()
    # correctly sharded — but shipped with an empty banked budget, so
    # the all-gather the replicated-param update inherently needs
    # reads as an unbudgeted collective on the hot path
    return _assemble(
        mesh, _base_fn(), param_spec=P(), opt_spec=dp,
        meta_extra={"collective_baseline": {
            "collectives": {},
            "tolerances": {"collective_count": {"rtol": 0.0, "atol": 0},
                           "collective_bytes": {"rtol": 0.0,
                                                "atol": 0}}}},
        description="sharded probe gated against an empty collective "
                    "budget")


def _inject_donation_through_pjit():
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    mesh = _mesh()
    dp = P("dp", None) if mesh.shape["dp"] > 1 else P()
    base = _base_fn()

    def probe(params, opt_state, x):
        new_p, new_o = base(params, opt_state, x)
        # the donated f32 shards no longer dtype-match the bf16
        # output shards: the alias drops on every device at once
        return new_p, {"m": new_o["m"].astype(jnp.bfloat16)}

    return _assemble(
        mesh, probe, param_spec=P(), opt_spec=dp,
        description="donated dp-sharded accumulator returned as bf16 "
                    "(alias dropped at shard shapes)")


def _inject_reshard_in_body():
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P
    mesh = _mesh()
    dp = P("dp", None) if mesh.shape["dp"] > 1 else P()
    flipped = P(None, "dp") if mesh.shape["dp"] > 1 else P()
    base = _base_fn()

    def probe(params, opt_state, x):
        new_p, new_o = base(params, opt_state, x)
        # the forced resharding constraint: flip the accumulator's
        # sharded axis mid-body; with out_shardings pinning it back to
        # P('dp', None) the partitioner must emit all-to-all both ways
        m = jax.lax.with_sharding_constraint(
            new_o["m"], NamedSharding(mesh, flipped))
        return new_p, {"m": m}

    return _assemble(
        mesh, probe, param_spec=P(), opt_spec=dp,
        description="forced resharding constraint flips the "
                    "accumulator axis mid-body (implicit all-to-all)")


MESH_INJECTIONS = {
    "sharding-dropped": _inject_sharding_dropped,
    "accidental-replication": _inject_accidental_replication,
    "collective-budget": _inject_collective_budget,
    "donation-through-pjit": _inject_donation_through_pjit,
    "reshard-in-body": _inject_reshard_in_body,
}


def build_injected_spec(defect):
    """The probe spec for ``defect`` (a MESH_INJECTIONS key), with
    ``injected`` stamped — the shaudit CLI's --inject positive
    control."""
    if defect not in MESH_INJECTIONS:
        raise ValueError(f"unknown injection {defect!r}; have "
                         f"{sorted(MESH_INJECTIONS)}")
    spec = MESH_INJECTIONS[defect]()
    spec["injected"] = defect
    return spec
