"""jxaudit built-in rules.

Each rule reads the :class:`~.core.ProgramContext` views it needs and
yields :class:`~.core.Finding`s with stable messages (the baseline
identity). A rule that cannot answer on this jax build records a
reason via ``ctx.degrade`` and yields nothing — degradation is a
non-gating note, exactly the xprof contract.

Thresholds are tuned for the registry's canonical audit shapes (tiny
2-layer models — HLO *structure*, not capacity, is what tier-1 audits):
at production shapes every threshold is conservative by orders of
magnitude, and a program registered via ``@audited`` at real shapes
gets the same absolute floors.
"""
import numpy as np

from .core import (Rule, register, iter_eqns, leaf_nbytes, np_dtype,
                   _dtype_name)

# an un-donated state arg smaller than this is not worth a finding
# (scalars, flags, RNG keys); the serving KV cache at the canonical
# audit shape is ~128 KiB, real optimizer state is GBs
DONATABLE_STATE_MIN_BYTES = 65536
# smallest low-precision tensor whose f32 upcast we flag — at the
# canonical shapes the weight matrices are 16-32 KiB
DTYPE_LEAK_MIN_BYTES = 16384
# smallest closure constant treated as "baked weights" rather than a
# legitimate trace-time table (iota vectors, causal masks)
BAKED_CONST_MIN_BYTES = 65536

# positional parameter names that mark an arg as replace-each-call
# state the caller could donate (the KV cache / optimizer-state naming
# convention the engine, TrainStep, heter PS and the optimizers share)
STATE_ARG_NAMES = frozenset({
    "caches", "cache", "kv_cache", "kv_caches", "cache_rows",
    "opt_state", "state", "grad_acc", "acc",
})

_LOW_FLOATS = ("bfloat16", "float16")
CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback",
})


def _fmt(dtype, shape):
    return f"{_dtype_name(dtype)}[{','.join(str(int(s)) for s in shape)}]"


@register
class DonationDropped(Rule):
    id = "donation-dropped"
    severity = "error"
    rationale = ("An arg declared in donate_argnums that XLA did not "
                 "actually alias to an output silently costs its full "
                 "HBM footprint twice per call — the donation is "
                 "dropped (dtype/shape mismatch with every output) "
                 "with only a one-time warning nobody reads.")

    def check(self, ctx):
        if not ctx.donate_argnums:
            return
        aliased = ctx.aliased_param_indices
        if aliased is None:
            ctx.degrade(self.id, "compiled HLO unavailable: "
                        + ctx.unavailable.get(
                            "hlo_text",
                            ctx.unavailable.get("aliased_params", "?")))
            return
        mapping = ctx.leaf_param_map
        if mapping is None:
            ctx.degrade(self.id,
                        "cannot map arg leaves onto compiled entry "
                        "parameters: "
                        + ctx.unavailable.get("leaf_param_map", "?"))
            return
        ranges = ctx.leaf_index_ranges()
        names = ctx.arg_names
        for argnum in ctx.donate_argnums:
            first, n = ranges.get(argnum, (0, 0))
            if n == 0:
                continue            # empty pytree: nothing to donate
            # a donated leaf the executable PRUNED (not in the map) is
            # dropped by definition — an unused arg cannot alias
            dropped = [i for i in range(first, first + n)
                       if mapping.get(i) not in aliased]
            if not dropped:
                continue
            label = (f"'{names[argnum]}'" if names
                     and argnum < len(names) else f"#{argnum}")
            wasted, reason = self._wasted_bytes(ctx, argnum, first,
                                                dropped)
            details = {"argnum": argnum, "dropped_leaves": dropped,
                       "declared_leaves": n, "wasted_bytes": wasted}
            if reason:
                details["wasted_bytes_reason"] = reason
            yield ctx.finding(
                self.id,
                f"donated arg {label}: {len(dropped)}/{n} buffers were "
                "not aliased by XLA — the donation was dropped "
                "(an output dtype/shape no longer matches the donated "
                "input)",
                severity=self.severity, details=details)

    @staticmethod
    def _wasted_bytes(ctx, argnum, first, dropped):
        """Transient duplicate HBM of the dropped leaves, from the
        compiled program's own input buffers. (None, reason) when this
        build can't answer — non-gating, the finding still stands."""
        try:
            leaves = dict(ctx.arg_leaves or [])[argnum]
            return sum(leaf_nbytes(leaves[i - first])
                       for i in dropped), None
        except Exception as e:
            return None, f"{type(e).__name__}: {e}"[:200]


@register
class DonationMissing(Rule):
    id = "donation-missing"
    severity = "warning"
    rationale = ("A large replace-each-call state arg (KV cache, "
                 "optimizer state) outside donate_argnums makes every "
                 "call transiently hold two copies of it in HBM; "
                 "donation lets XLA update it in place.")

    def check(self, ctx):
        names = ctx.arg_names
        if names is None:
            ctx.degrade(self.id, "positional arg names unavailable "
                        "(prebuilt jitted spec without arg_names)")
            return
        donated = set(ctx.donate_argnums)
        for argnum, leaves in ctx.arg_leaves or []:
            if argnum in donated or argnum >= len(names):
                continue
            name = names[argnum]
            if name not in STATE_ARG_NAMES or not leaves:
                continue
            nbytes = sum(leaf_nbytes(l) for l in leaves)
            if nbytes < DONATABLE_STATE_MIN_BYTES:
                continue
            yield ctx.finding(
                self.id,
                f"state arg '{name}' (#{argnum}) is never donated: the "
                "caller replaces it each call, so donate_argnums would "
                "let XLA update it in place instead of holding two "
                "copies",
                severity=self.severity,
                details={"argnum": argnum, "bytes": nbytes,
                         "leaves": len(leaves)})


@register
class DtypeLeak(Rule):
    id = "dtype-leak"
    severity = "warning"
    rationale = ("convert_element_type upcasts of large tensors to "
                 "f32/f64 inside a low-precision program double the "
                 "HBM stream on the exact paths bf16 exists to halve, "
                 "and break producer-consumer fusion; f64 anywhere on "
                 "a device path is an x64 leak.")

    def check(self, ctx):
        cj = ctx.closed_jaxpr
        if cj is None:
            ctx.degrade(self.id, "jaxpr unavailable: "
                        + ctx.unavailable.get("jaxpr", "?"))
            return
        census = ctx.float_census()
        low_dominated = census["low_elems"] > (census["f32_elems"]
                                               + census["f64_elems"])
        f64_seen = set()
        for var in self._all_vars(cj):
            aval = getattr(var, "aval", None)
            dt = np_dtype(getattr(aval, "dtype", None))
            if dt is not None and dt == np.dtype(np.float64):
                key = _fmt(dt, getattr(aval, "shape", ()))
                if key not in f64_seen:
                    f64_seen.add(key)
                    yield ctx.finding(
                        self.id,
                        f"float64 value {key} on the device path — an "
                        "x64 leak (double the bytes of f32 and no TPU "
                        "support)",
                        severity="error",
                        details={"dtype": "float64"})
        if not low_dominated:
            return
        seen = {}
        for eqn in iter_eqns(cj.jaxpr):
            if getattr(eqn.primitive, "name",
                       str(eqn.primitive)) != "convert_element_type":
                continue
            new_dt = np_dtype(eqn.params.get("new_dtype"))
            aval = getattr(eqn.invars[0], "aval", None)
            old_dt = np_dtype(getattr(aval, "dtype", None))
            if new_dt is None or old_dt is None:
                continue
            if _dtype_name(old_dt) not in _LOW_FLOATS \
                    or new_dt.name not in ("float32", "float64"):
                continue
            nbytes = int(np.prod(aval.shape, dtype=np.int64)) \
                * old_dt.itemsize
            if nbytes < DTYPE_LEAK_MIN_BYTES:
                continue
            key = (_fmt(old_dt, aval.shape), new_dt.name)
            seen[key] = seen.get(key, 0) + 1
        for (old, new), count in sorted(seen.items()):
            for _ in range(count):
                yield ctx.finding(
                    self.id,
                    f"{old} -> {new} upcast on the device path of a "
                    "low-precision-dominated program (doubles the HBM "
                    "stream and splits fusions at the conversion)",
                    severity=self.severity,
                    details={"from": old, "to": new})

    @staticmethod
    def _all_vars(cj):
        yield from cj.jaxpr.constvars
        yield from cj.jaxpr.invars
        for eqn in iter_eqns(cj.jaxpr):
            yield from eqn.outvars


@register
class BakedConstant(Rule):
    id = "baked-constant"
    severity = "error"
    rationale = ("A weight-sized array captured by closure becomes a "
                 "compile-time constant: it is duplicated into every "
                 "compiled variant's HBM and changing its VALUE means "
                 "a full recompile — thread it as an argument instead.")

    def check(self, ctx):
        cj = ctx.closed_jaxpr
        if cj is None:
            ctx.degrade(self.id, "jaxpr unavailable: "
                        + ctx.unavailable.get("jaxpr", "?"))
            return
        for const in getattr(cj, "consts", ()):
            shape = getattr(const, "shape", None)
            dtype = getattr(const, "dtype", None)
            if shape is None or dtype is None:
                continue
            nbytes = leaf_nbytes(const)
            if nbytes < BAKED_CONST_MIN_BYTES:
                continue
            yield ctx.finding(
                self.id,
                f"closure-captured constant {_fmt(dtype, shape)} "
                f"({nbytes} bytes) baked into the program — duplicated "
                "HBM per compiled variant and a recompile per value; "
                "pass it as an argument",
                severity=self.severity,
                details={"bytes": nbytes})


@register
class HostCallback(Rule):
    id = "host-callback"
    severity = "error"
    rationale = ("pure_callback / io_callback / debug_callback (incl. "
                 "jax.debug.print) in a hot program force a device-to-"
                 "host round trip every call — the decode-wave latency "
                 "cliff telemetry keeps finding after the fact.")

    def check(self, ctx):
        cj = ctx.closed_jaxpr
        if cj is None:
            ctx.degrade(self.id, "jaxpr unavailable: "
                        + ctx.unavailable.get("jaxpr", "?"))
            return
        for eqn in iter_eqns(cj.jaxpr):
            name = getattr(eqn.primitive, "name", str(eqn.primitive))
            if name in CALLBACK_PRIMITIVES:
                yield ctx.finding(
                    self.id,
                    f"host callback primitive '{name}' reachable in "
                    "this program (device->host round trip per call); "
                    "hoist it out of the hot path or gate it behind a "
                    "debug build",
                    severity=self.severity,
                    details={"primitive": name})
