"""jxaudit positive controls: deliberately introduce each defect class.

``inject_spec(spec, defect)`` returns a modified COPY of a raw-``fn``
program spec (canonically the serving decode wave) carrying exactly one
of the defect classes the rules exist to catch. The CLI's
``--inject CLASS`` audits that copy and must exit 1 — tier-1 proves the
gate fires (`tests/test_jxaudit.py`), the same contract as ptlint's
decode-wave float() injection and hlo_audit's degrade(). Never usable
with ``--baseline-update``.

Each injection is surgical: it introduces its own defect without
tripping the other rules, so a ``--select``-narrowed audit of the
injected copy attributes the exit-1 to the intended rule.
"""
from .rules import BAKED_CONST_MIN_BYTES, DTYPE_LEAK_MIN_BYTES


def _wrap_dropped_donation(spec):
    """Cast the program's float32 outputs to bf16: the donated f32
    input buffers (the batched KV cache) no longer dtype-match any
    output, so XLA silently drops the donation — the exact failure a
    refactor that changes an output dtype produces."""
    import jax
    import jax.numpy as jnp
    fn = spec["fn"]

    def injected(*args, **kwargs):
        out = fn(*args, **kwargs)
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if getattr(x, "dtype", None) == jnp.float32 else x, out)

    return dict(spec, fn=injected, jitted=None)


def _wrap_dtype_leak(spec):
    """Feed the program bf16 weights and upcast them back to f32 at
    entry: the program becomes low-precision-dominated with large
    bf16 -> f32 convert_element_type ops on the device path — the
    bf16-KV-cache-upcast-in-the-decode-wave hazard."""
    import jax
    import jax.numpy as jnp
    fn = spec["fn"]

    def down(x):
        if getattr(x, "dtype", None) == jnp.float32 \
                and x.nbytes >= DTYPE_LEAK_MIN_BYTES:
            return x.astype(jnp.bfloat16)
        return x

    def up(x):
        if getattr(x, "dtype", None) == jnp.bfloat16:
            return x.astype(jnp.float32)
        return x

    # only the params arg (argnum 0) is downcast: the donated caches
    # keep their dtype, so donation stays intact and the injected copy
    # trips dtype-leak alone
    args = list(spec["args"])
    args[0] = jax.tree_util.tree_map(down, args[0])

    def injected(params, *rest, **kwargs):
        return fn(jax.tree_util.tree_map(up, params), *rest, **kwargs)

    return dict(spec, fn=injected, args=tuple(args), jitted=None)


def _wrap_baked_constant(spec):
    """Close over a weight-sized array: it lands in the jaxpr's consts
    — baked into the executable instead of threaded as an argument."""
    import jax.numpy as jnp
    fn = spec["fn"]
    n = BAKED_CONST_MIN_BYTES // 4 * 4        # comfortably past threshold
    baked = jnp.arange(n, dtype=jnp.float32).reshape(4, n // 4)

    def injected(*args, **kwargs):
        out = fn(*args, **kwargs)
        return out, jnp.sum(baked * 1e-9)

    return dict(spec, fn=injected, jitted=None)


def _wrap_host_callback(spec):
    """Put a jax.debug.print on the hot path: a debug_callback
    primitive (device->host round trip) reachable per call."""
    import jax
    fn = spec["fn"]

    def injected(*args, **kwargs):
        out = fn(*args, **kwargs)
        leaf = jax.tree_util.tree_leaves(out)[0]
        jax.debug.print("jxaudit-injected callback: {x}",
                        x=leaf.reshape(-1)[0])
        return out

    return dict(spec, fn=injected, jitted=None)


INJECTIONS = {
    "donation-dropped": _wrap_dropped_donation,
    "dtype-leak": _wrap_dtype_leak,
    "baked-constant": _wrap_baked_constant,
    "host-callback": _wrap_host_callback,
}


def inject_spec(spec, defect):
    """Modified copy of ``spec`` carrying ``defect`` (an INJECTIONS
    key). The spec must expose a raw ``fn`` to wrap."""
    if defect not in INJECTIONS:
        raise ValueError(f"unknown injection {defect!r}; have "
                         f"{sorted(INJECTIONS)}")
    if spec.get("fn") is None:
        raise ValueError(f"program {spec['name']!r} exposes no raw fn "
                         "to inject into")
    out = INJECTIONS[defect](spec)
    out["injected"] = defect
    return out
