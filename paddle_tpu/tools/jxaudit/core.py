"""jxaudit core: program context, rule registry, audit driver.

A *program spec* is the same dict shape the xprof registry builds
(``{name, fn | jitted, args, jit_kwargs, description}``) extended with
the donation metadata jxaudit's rules consume:

  * ``donate_argnums`` — the argnums the program's jit wrapper declares
    (for raw-``fn`` specs this defaults to ``jit_kwargs``'s value; for
    prebuilt ``jitted`` specs the builder must pass it explicitly —
    jax 0.4.37's PjitFunction exposes no public donate introspection);
  * ``arg_names`` — positional parameter names, used by the
    donatable-state heuristic (defaults to ``inspect.signature(fn)``).

``ProgramContext`` wraps one spec and lazily computes the three views
rules read, each independently degradable (a jax build that can't
answer one question must not cost us the others — the failure is
recorded as a reason string under ``unavailable`` instead of raised,
the xprof contract):

  * ``closed_jaxpr``  — ``jitted.trace(*args).jaxpr`` (consts + eqns;
    no compile), falling back to ``jax.make_jaxpr`` on builds without
    ``.trace``;
  * ``hlo_text`` / ``aliased_param_indices`` — the compiled
    executable's optimized-HLO text and the parsed
    ``input_output_alias`` header. The header is the *actual* aliasing
    XLA committed to, and — unlike ``memory_analysis()``'s
    ``alias_size_in_bytes`` — it survives persistent-cache loads, so
    the donation rule is deterministic warm or cold;
  * flat-leaf accounting — ``donate_argnums`` is declared per pytree
    *arg*, the HLO header speaks flat *parameter indices*; the context
    maps between them (leaves flatten in argument order).
"""
import inspect

import numpy as np


def _reason(exc):
    return f"{type(exc).__name__}: {exc}"[:300]


def leaf_nbytes(leaf):
    """HBM footprint of one pytree leaf (arrays or python scalars)."""
    nb = getattr(leaf, "nbytes", None)
    if isinstance(nb, (int, np.integer)):
        return int(nb)
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return np.asarray(leaf).nbytes


def np_dtype(dtype):
    """np.dtype(dtype), or None for jax extended dtypes (PRNG keys,
    float8 variants numpy can't interpret) — callers skip those.
    None maps to None (np.dtype(None) would be float64!)."""
    if dtype is None:
        return None
    try:
        return np.dtype(dtype)
    except TypeError:
        return None


def _dtype_name(dtype):
    dt = np_dtype(dtype)
    return dt.name if dt is not None else str(dtype)


class Finding:
    """One program-level audit hit.

    ``message`` must be stable across unrelated edits (deterministic
    shapes/dtypes are fine, volatile measurements are not) — the
    baseline fingerprint is (rule, program, message), the same identity
    contract as ptlint. Quantifications that may degrade (wasted bytes
    from the compiled analysis) ride in ``details`` instead.
    """

    __slots__ = ("rule", "program", "severity", "message", "details")

    def __init__(self, rule, program, message, severity="error",
                 details=None):
        self.rule = rule
        self.program = program
        self.message = message
        self.severity = severity
        self.details = dict(details or {})

    @property
    def fingerprint(self):
        return f"{self.rule}::{self.program}::{self.message}"

    @property
    def path(self):
        """Alias: the program name doubles as ptlint's `path` slot so
        jxaudit reuses the lint baseline machinery (load/diff/update/
        undocumented) unchanged — one justified-baseline contract
        across both analyzers."""
        return self.program

    def to_dict(self):
        return {"rule": self.rule, "program": self.program,
                "severity": self.severity, "message": self.message,
                "details": self.details}

    def render(self):
        return f"{self.program}: [{self.rule}/{self.severity}] " \
               f"{self.message}"

    def __repr__(self):
        return f"Finding({self.render()!r})"


class Rule:
    id = None
    severity = "error"
    rationale = ""

    def check(self, ctx):
        raise NotImplementedError
        yield  # pragma: no cover


RULES = {}


def register(cls):
    """Class decorator: instantiate and add to the rule registry."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    RULES[inst.id] = inst
    return cls


# ---------------------------------------------------------------------------
# HLO input_output_alias header parsing
# ---------------------------------------------------------------------------

def parse_alias_header(hlo_text):
    """Flat parameter indices the compiled module actually aliases to an
    output, from the ``input_output_alias={ {out}: (param, {index},
    may-alias), ... }`` entry on the HloModule header line. A module
    with no donation committed has no header entry at all — that reads
    as the empty set, which is exactly what a fully-dropped donation
    looks like."""
    header = hlo_text.split("\n", 1)[0]
    key = "input_output_alias={"
    start = header.find(key)
    if start < 0:
        return set()
    depth, i = 1, start + len(key)
    while i < len(header) and depth:
        if header[i] == "{":
            depth += 1
        elif header[i] == "}":
            depth -= 1
        i += 1
    body = header[start + len(key):i - 1]
    import re
    return {int(m.group(1))
            for m in re.finditer(r"\(\s*(\d+)\s*,\s*\{[^}]*\}", body)}


_HLO_DTYPE_ABBREV = {
    "float32": "f32", "float64": "f64", "float16": "f16",
    "bfloat16": "bf16", "int8": "s8", "int16": "s16", "int32": "s32",
    "int64": "s64", "uint8": "u8", "uint16": "u16", "uint32": "u32",
    "uint64": "u64", "bool": "pred", "complex64": "c64",
    "complex128": "c128",
}


def aval_type_str(aval, shape=None):
    """HLO-style type string for an aval/array (``f32[64,64]``), or
    None when the dtype has no HLO text spelling we can predict (jax
    extended dtypes) — callers treat None as a wildcard. ``shape``
    overrides the aval's shape (the sharded-program case: the
    partitioned module's entry parameters carry PER-SHARD shapes)."""
    dt = np_dtype(getattr(aval, "dtype", None))
    if dt is None:
        return None
    ab = _HLO_DTYPE_ABBREV.get(dt.name)
    if ab is None:
        return None
    if shape is None:
        shape = getattr(aval, "shape", ())
    return f"{ab}[{','.join(str(int(s)) for s in shape)}]"


def leaf_shard_shape(leaf):
    """The per-device shape of one concrete arg leaf, or None when the
    leaf carries no sharding to ask (plain numpy/scalars). For a
    replicated or single-device jax.Array this equals the full shape;
    for a dp-sharded leaf it is the slice each device holds — which is
    exactly how the leaf appears in the partitioned module's
    entry_computation_layout."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None:
        return None
    try:
        return tuple(int(s) for s in sharding.shard_shape(leaf.shape))
    except Exception:
        return None


def parse_entry_param_types(hlo_text):
    """Entry parameter type strings (layout braces stripped) from the
    header's ``entry_computation_layout={(p0, p1, ...)->...}``, or None
    when the header doesn't parse. jit's default ``keep_unused=False``
    PRUNES unused args from the executable, so this list can be
    SHORTER than the flat arg leaves — ``align_leaves_to_params``
    reconciles the two numberings for the donation rule."""
    import re
    header = hlo_text.split("\n", 1)[0]
    key = "entry_computation_layout={("
    start = header.find(key)
    if start < 0:
        return None
    i = start + len(key)
    depth, buf, parts = 1, [], []
    while i < len(header):
        c = header[i]
        if c in "({[":
            depth += 1
        elif c in ")}]":
            depth -= 1
            if depth == 0:
                break
        if c == "," and depth == 1:
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(c)
        i += 1
    if depth != 0:
        return None
    if "".join(buf).strip():
        parts.append("".join(buf))
    # strip the /*index=N*/ comments XLA interleaves and the {layout}
    return [re.sub(r"\{[^{}]*\}", "",
                   re.sub(r"/\*.*?\*/", "", p)).strip() for p in parts]


def parse_entry_param_shardings(hlo_text):
    """{entry_param_index: sharding_string} from the ``parameter(N),
    sharding={...}`` instruction lines of partitioned optimized HLO —
    the sharding XLA COMMITTED each entry parameter to (``{replicated}``,
    ``{devices=[8,1]<=[8]}``, ...), which is what the mesh-aware rules
    compare declarations against. Returns ``{}`` when no parameter
    carries an annotation (an unpartitioned module, or a build that
    strips them) and None when the same index appears with two different
    sharding strings (nested computations colliding with the entry —
    misattributing a sharding is worse than not answering)."""
    import re
    out = {}
    pat = re.compile(r"=\s*(?:\([^)]*\)|\S+)\s+parameter\((\d+)\)"
                     r"\s*,\s*sharding=(\{)")
    for line in hlo_text.splitlines():
        m = pat.search(line)
        if not m:
            continue
        idx = int(m.group(1))
        i = m.start(2)
        depth, j = 0, i
        while j < len(line):
            if line[j] == "{":
                depth += 1
            elif line[j] == "}":
                depth -= 1
                if depth == 0:
                    break
            j += 1
        if depth != 0:
            continue
        sharding = line[i:j + 1]
        if idx in out and out[idx] != sharding:
            return None
        out[idx] = sharding
    return out


def align_leaves_to_params(leaf_types, param_types):
    """Greedy order-preserving alignment of flat arg leaves onto the
    compiled module's entry parameters -> ({leaf_index: param_index},
    None) or (None, reason). Leaves the executable pruned are skipped;
    a None leaf type is a wildcard (extended dtypes). The alignment
    degrades instead of guessing when it could be wrong: a param no
    leaf matches, or a pruned leaf whose type also occurs among the
    KEPT parameters (a same-typed pruned/kept pair is indistinguishable
    from text, and misattributing donation aliasing is worse than not
    answering)."""
    mapping, li, n = {}, 0, len(leaf_types)
    for pi, pt in enumerate(param_types):
        matched = False
        while li < n:
            lt = leaf_types[li]
            if lt is None or lt == pt:
                mapping[li] = pi
                li += 1
                matched = True
                break
            li += 1                       # this leaf was pruned
        if not matched:
            return None, (f"no arg leaf lines up with compiled entry "
                          f"parameter {pi} ({pt})")
    unmatched = [i for i in range(n) if i not in mapping]
    params = set(param_types)
    ambiguous = sorted({str(leaf_types[i]) for i in unmatched
                        if leaf_types[i] is None
                        or leaf_types[i] in params})
    if ambiguous:
        return None, ("pruned-arg alignment ambiguous: unused leaf "
                      f"type(s) {ambiguous} also occur among kept "
                      "parameters")
    return mapping, None


def iter_eqns(jaxpr):
    """Every eqn in ``jaxpr`` and (recursively) in any sub-jaxpr carried
    in eqn params — scan/cond/while bodies, pjit calls, custom-vjp
    branches. Duck-typed (``.jaxpr`` unwraps a ClosedJaxpr, ``.eqns``
    marks a Jaxpr) so it tracks no jax.core deprecation churn."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def _sub_jaxprs(v):
    inner = getattr(v, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        yield inner
    elif hasattr(v, "eqns"):
        yield v
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _sub_jaxprs(x)


# ---------------------------------------------------------------------------
# per-program context
# ---------------------------------------------------------------------------

class ProgramContext:
    """Everything rules need about one tracked program, computed lazily
    and at most once. ``unavailable`` maps analysis/rule id -> reason
    string for everything this jax build could not answer."""

    def __init__(self, spec):
        self.spec = spec
        self.name = spec["name"]
        self.args = spec.get("args", ())
        self.jit_kwargs = dict(spec.get("jit_kwargs") or {})
        donate = spec.get("donate_argnums",
                          self.jit_kwargs.get("donate_argnums", ()))
        self.donate_argnums = tuple(sorted(donate or ()))
        self.unavailable = {}
        self._cache = {}

    def _cached(self, key, build):
        if key not in self._cache:
            try:
                self._cache[key] = build()
            except Exception as e:
                self.unavailable.setdefault(key, _reason(e))
                self._cache[key] = None
        return self._cache[key]

    # ------------------------------------------------------------- jitted
    @property
    def jitted(self):
        def build():
            if self.spec.get("jitted") is not None:
                return self.spec["jitted"]
            import jax
            return jax.jit(self.spec["fn"], **self.jit_kwargs)
        return self._cached("jitted", build)

    # ---------------------------------------------------------- arg names
    @property
    def arg_names(self):
        """Positional parameter names, or None when unknowable (prebuilt
        jitted spec without explicit ``arg_names``)."""
        names = self.spec.get("arg_names")
        if names:
            return tuple(names)
        fn = self.spec.get("fn")
        if fn is None:
            return None
        try:
            params = inspect.signature(fn).parameters.values()
        except (TypeError, ValueError):
            return None
        return tuple(p.name for p in params
                     if p.kind in (p.POSITIONAL_ONLY,
                                   p.POSITIONAL_OR_KEYWORD))

    # ------------------------------------------------------- flat leaves
    @property
    def arg_leaves(self):
        """[(argnum, [leaf, ...]), ...] in flattening order."""
        def build():
            import jax
            return [(i, jax.tree_util.tree_leaves(a))
                    for i, a in enumerate(self.args)]
        return self._cached("arg_leaves", build)

    def leaf_index_ranges(self):
        """{argnum: (first_flat_index, n_leaves)} — how the HLO module's
        flat parameter numbering maps back onto pytree args."""
        out, offset = {}, 0
        for argnum, leaves in self.arg_leaves or []:
            out[argnum] = (offset, len(leaves))
            offset += len(leaves)
        return out

    # ------------------------------------------------------------- jaxpr
    @property
    def closed_jaxpr(self):
        def build():
            jitted = self.jitted
            if jitted is not None and hasattr(jitted, "trace"):
                return jitted.trace(*self.args).jaxpr
            import jax
            if self.spec.get("fn") is None:
                raise RuntimeError(
                    "no .trace() on this jax build and the spec carries "
                    "no raw fn for make_jaxpr")
            return jax.make_jaxpr(self.spec["fn"])(*self.args)
        return self._cached("jaxpr", build)

    # ----------------------------------------------------- compiled view
    @property
    def hlo_text(self):
        def build():
            jitted = self.jitted
            if jitted is None:
                raise RuntimeError("jit wrapper unavailable")
            return jitted.lower(*self.args).compile().as_text()
        return self._cached("hlo_text", build)

    @property
    def aliased_param_indices(self):
        """Compiled-entry parameter indices XLA actually aliased, or
        None (+reason) when the compiled text is unavailable."""
        def build():
            text = self.hlo_text
            if text is None:
                raise RuntimeError(
                    "compiled HLO unavailable: "
                    + self.unavailable.get("hlo_text", "unknown"))
            return parse_alias_header(text)
        return self._cached("aliased_params", build)

    @property
    def entry_param_shardings(self):
        """{entry_param_index: committed sharding string} parsed from
        the partitioned module's ``parameter(N), sharding={...}`` lines,
        or None (+reason) when the compiled text is unavailable, carries
        no annotations at all, or annotates one index two ways. The
        all-or-nothing posture is deliberate: a module without
        annotations (single-device build, or a jax that stops printing
        them) must degrade every mesh rule, not read as 'everything
        replicated'."""
        def build():
            text = self.hlo_text
            if text is None:
                raise RuntimeError(
                    "compiled HLO unavailable: "
                    + self.unavailable.get("hlo_text", "unknown"))
            ann = parse_entry_param_shardings(text)
            if ann is None:
                raise RuntimeError(
                    "conflicting parameter sharding annotations in the "
                    "compiled text")
            if not ann:
                raise RuntimeError(
                    "compiled text carries no parameter sharding "
                    "annotations (unpartitioned module, or a jax build "
                    "that strips them)")
            return ann
        return self._cached("entry_param_shardings", build)

    @property
    def leaf_param_map(self):
        """{flat_arg_leaf_index: compiled_entry_parameter_index}, or
        None (+reason) when the two numberings can't be reconciled —
        jit's keep_unused=False prunes unused args from the executable,
        so the map comes from a type-based alignment rather than
        assumed identity (see align_leaves_to_params)."""
        def build():
            text = self.hlo_text
            if text is None:
                raise RuntimeError(
                    "compiled HLO unavailable: "
                    + self.unavailable.get("hlo_text", "unknown"))
            params = parse_entry_param_types(text)
            if params is None:
                raise RuntimeError(
                    "entry_computation_layout header unparseable")
            leaves = [l for _, ls in (self.arg_leaves or []) for l in ls]
            cj = self.closed_jaxpr
            if cj is not None \
                    and len(cj.jaxpr.invars) == len(leaves):
                # invars carry the CANONICALIZED avals (python floats
                # become weak f32) — what the HLO params actually are.
                # The SHAPE comes from the concrete leaf's per-device
                # shard when it has one: a partitioned (SPMD) module's
                # entry parameters are the per-shard slices, so a
                # dp-sharded f32[128] opt-state leaf shows up as
                # f32[16] on the dp=8 mesh.
                types = [aval_type_str(v.aval, shape=leaf_shard_shape(l))
                         for v, l in zip(cj.jaxpr.invars, leaves)]
            else:
                types = [aval_type_str(l, shape=leaf_shard_shape(l))
                         for l in leaves]
            mapping, reason = align_leaves_to_params(types, params)
            if mapping is None:
                raise RuntimeError(reason)
            return mapping
        return self._cached("leaf_param_map", build)

    # ------------------------------------------------------ float census
    def float_census(self):
        """Float bytes AND element counts by precision class over the
        program's input leaves and closure consts. Elements, not bytes,
        are the domination metric (a bf16 model's weights hold twice
        the values per byte — bytes would undercount exactly the
        tensors that make a program low-precision)."""
        out = {"low_bytes": 0, "f32_bytes": 0, "f64_bytes": 0,
               "low_elems": 0, "f32_elems": 0, "f64_elems": 0}
        leaves = [l for _, ls in (self.arg_leaves or []) for l in ls]
        cj = self.closed_jaxpr
        if cj is not None:
            leaves += list(getattr(cj, "consts", ()))
        import jax.numpy as jnp
        low = (np.dtype(jnp.bfloat16), np.dtype(np.float16))
        for leaf in leaves:
            dt = np_dtype(getattr(leaf, "dtype", None))
            if dt is None:
                continue
            cls = ("low" if dt in low else
                   "f32" if dt == np.dtype(np.float32) else
                   "f64" if dt == np.dtype(np.float64) else None)
            if cls is None:
                continue
            nb = leaf_nbytes(leaf)
            out[f"{cls}_bytes"] += nb
            out[f"{cls}_elems"] += nb // dt.itemsize
        return out

    # ----------------------------------------------------------- helpers
    def finding(self, rule, message, severity="error", details=None):
        return Finding(rule, self.name, message, severity=severity,
                       details=details)

    def degrade(self, rule_id, reason):
        self.unavailable.setdefault(rule_id, str(reason)[:300])


# ---------------------------------------------------------------------------
# audit driver
# ---------------------------------------------------------------------------

SCHEMA_VERSION = 1


def audit_programs(specs, select=None, rules=None):
    """Run every (selected) rule over every spec.

    ``rules`` is the registry to drive (default: the module-global
    ``RULES``); the mesh-aware family passes its own registry
    (mesh_rules.MESH_RULES) so the two rule sets stay disjoint CLIs
    over one driver.

    Returns ``(findings, report)``: findings is the flat
    line-of-defense list (baseline-diffed by the CLI), report is the
    JSON-able per-program document — description, per-rule finding
    counts, and the ``unavailable`` reasons for every analysis this jax
    build could not answer (null-style degradation, never a crash; an
    unexpectedly *raising* rule is recorded there too)."""
    import jax
    if rules is None:
        rules = RULES
    if select is not None:
        unknown = set(select) - set(rules)
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}; "
                             f"registry has {sorted(rules)}")
    findings, programs = [], {}
    for spec in specs:
        ctx = ProgramContext(spec)
        per_rule = {}
        for rule_id, rule in sorted(rules.items()):
            if select is not None and rule_id not in select:
                continue
            try:
                hits = list(rule.check(ctx))
            except Exception as e:     # a rule must degrade, not abort
                ctx.degrade(rule_id, _reason(e))
                hits = []
            if hits:
                per_rule[rule_id] = len(hits)
            findings.extend(hits)
        row = {"findings": per_rule,
               "donate_argnums": list(ctx.donate_argnums)}
        if spec.get("description"):
            row["description"] = spec["description"]
        if spec.get("injected"):
            row["injected"] = True
        if ctx.unavailable:
            row["unavailable"] = dict(ctx.unavailable)
        programs[ctx.name] = row
    findings.sort(key=lambda f: (f.program, f.rule, f.message))
    report = {
        "schema": SCHEMA_VERSION,
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "programs": programs,
    }
    return findings, report


def summarize(findings, report):
    """Compact counts-per-rule summary (the journal payload)."""
    by_rule = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "findings": len(findings),
        "by_rule": dict(sorted(by_rule.items())),
        "programs": len(report.get("programs", {})),
        "degraded": sum(1 for row in report.get("programs", {}).values()
                        if row.get("unavailable")),
    }


def publish_summary(findings, report, recorder=None, **extra):
    """Journal a ``jxaudit`` summary event (counts per rule) through
    ``recorder`` or the current flight recorder, so a run journal shows
    the audit verdict next to the compile / xla_program events it
    contextualizes. Pass the POST-baseline findings (the CLI does) so
    the journaled verdict matches the exit code; justified suppressions
    ride along via ``suppressed=N``. No-op without a recorder."""
    from ...utils import flight_recorder as fr
    rec = recorder if recorder is not None else fr.get_recorder()
    if rec is None:
        return None
    s = summarize(findings, report)
    return rec.jxaudit(findings=s["findings"], by_rule=s["by_rule"],
                       programs=s["programs"], degraded=s["degraded"],
                       **extra)
