"""Developer tooling that ships with the library: static analysis
(`tools.lint`, scripts/ptlint.py) and the XLA program observatory
(`tools.xprof`, scripts/hlo_audit.py)."""
